"""Fleet-wide request tracing (marker: tracing): traceparent context
mint/parse/propagation, the span store's tail-based sampling and merge
dedupe, end-to-end merged waterfalls (disaggregated prefill ≥90% wall
coverage, kill-mid-run reroute showing BOTH replicas, preempt/resume,
speculative draft/verify), incident events naming the victim request's
trace id, the /traces live endpoint, the dstpu-trace CLI, the
dstpu-telemetry tracing section with TTFT exemplar links, and the
host-sync cleanliness of the trace bookkeeping in the decode hot path.
"""
import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (
    InferenceEngineV2,
    RaggedInferenceEngineConfig,
)
from deepspeed_tpu.inference.v2.lifecycle import (
    LifecycleScheduler,
    RequestState,
    ServeRequest,
)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.telemetry.tracing import (
    RequestTraceStore,
    TraceContext,
    get_trace_store,
    install_trace_store,
    span_coverage,
)

pytestmark = pytest.mark.tracing


@pytest.fixture(scope="module")
def tiny_lm():
    cfg = TransformerConfig.tiny(use_flash=False)
    model = CausalLM(cfg)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(tiny_lm, **kw):
    model, params = tiny_lm
    defaults = dict(max_tokens=32, max_seqs=4, max_ctx=64, block_size=8,
                    dtype=jnp.float32, attn_impl="gather")
    defaults.update(kw)
    return InferenceEngineV2(model, params,
                             RaggedInferenceEngineConfig(**defaults))


@pytest.fixture(scope="module")
def shared_eng(tiny_lm):
    """One engine shared by the scheduler-level tests — compiles once."""
    return _engine(tiny_lm)


@pytest.fixture(autouse=True)
def fresh_store():
    """Every test gets a clean process-global store (sample_every=1 so
    assertions never race the sampling counter); always uninstalled after
    so other suites see tracing disabled."""
    store = RequestTraceStore(sample_every=1)
    install_trace_store(store)
    yield store
    install_trace_store(None)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------- #
# Context wire format
# --------------------------------------------------------------------- #
class TestTraceContext:
    def test_mint_parse_roundtrip(self):
        c = TraceContext.mint()
        h = c.header()
        assert h.startswith("00-") and len(h) == 55
        assert TraceContext.parse(h) == c

    def test_parse_rejects_malformed(self):
        for bad in (None, "", "garbage", "00-short-xy-01",
                    "99-" + "a" * 32 + "-" + "b" * 16 + "-01"):
            assert TraceContext.parse(bad) is None

    def test_child_keeps_trace_id_fresh_span_id(self):
        c = TraceContext.mint()
        k = c.child()
        assert k.trace_id == c.trace_id and k.span_id != c.span_id

    def test_from_request_header_wins_over_body(self):
        a, b = TraceContext.mint(), TraceContext.mint()
        got = TraceContext.from_request({"traceparent": a.header()},
                                        {"traceparent": b.header()})
        assert got.trace_id == a.trace_id
        got = TraceContext.from_request({}, {"traceparent": b.header()})
        assert got.trace_id == b.trace_id
        # nothing carried → fresh mint, sampled by default
        got = TraceContext.from_request({}, {})
        assert got.trace_id not in (a.trace_id, b.trace_id)
        assert got.sampled


# --------------------------------------------------------------------- #
# Store: tail sampling, merge dedupe, exemplars
# --------------------------------------------------------------------- #
class TestStoreSampling:
    def test_steady_state_sampled_one_in_n(self):
        s = RequestTraceStore(sample_every=5)
        kept = 0
        for i in range(20):
            tid = f"{i:032x}"
            s.add_span(tid, "decode_window", t0=time.time(), dur_s=0.001)
            kept += 1 if s.finish(tid, wall_s=0.01)["kept"] else 0
        assert kept == 4                      # 1-in-5 of 20
        assert s.counters["trace/dropped"] == 16

    def test_flagged_always_kept(self):
        s = RequestTraceStore(sample_every=1000)
        for i, flag in enumerate(("shed", "preempted", "rerouted",
                                  "nan_isolated", "deadline_expired")):
            tid = f"f{i:031x}"
            s.add_span(tid, "queue_wait", t0=0.0, dur_s=0.0)
            rec = s.finish(tid, flag=flag, wall_s=0.01)
            if i == 0:
                assert rec["kept"]            # seq 0 sampled anyway
            else:
                assert rec["kept"] and rec["flags"] == [flag]
        assert s.counters["trace/flagged"] == 5

    def test_exemplar_holder_kept_and_bounded(self):
        s = RequestTraceStore(sample_every=1000, exemplar_k=2)
        s.finish("0" * 32, wall_s=0.01)       # seq 0: burn the free keep
        for i in range(1, 4):
            tid = f"{i:032x}"
            assert s.note_exemplar("ttft_s", float(i), tid)
            # a current exemplar holder is always kept → the link resolves
            assert s.finish(tid, wall_s=0.01)["kept"]
        # set is [3, 2]: a smaller offer is rejected and its trace
        # follows normal sampling (here: dropped)
        tid = f"{9:032x}"
        assert not s.note_exemplar("ttft_s", 1.5, tid)
        assert not s.finish(tid, wall_s=0.01)["kept"]
        ex = s.exemplars()["ttft_s"]
        assert [e["value"] for e in ex] == [3.0, 2.0]

    def test_slow_cohort_kept(self):
        s = RequestTraceStore(sample_every=10**6, slow_min_samples=10,
                              slow_quantile=0.9)
        for i in range(1, 40):
            tid = f"{i:032x}"
            wall = 10.0 if i == 30 else 0.01  # one outlier past the p90
            rec = s.finish(tid, wall_s=wall)
            if i == 30:
                assert rec["kept"]

    def test_merge_dedupes_by_sid_and_carries_flags(self):
        a, b = RequestTraceStore(), RequestTraceStore()
        tid = "a" * 32
        a.add_span(tid, "prefill", t0=1.0, dur_s=0.5, component="serve:1")
        payload = a.finish(tid, flag="rerouted", wall_s=1.0)
        assert b.merge(tid, payload) == 1
        assert b.merge(tid, payload) == 0     # idempotent re-merge
        rec = b.finish(tid, wall_s=2.0)
        assert rec["kept"] and "rerouted" in rec["flags"]
        assert len(rec["spans"]) == 1

    def test_drop_then_keep_upgrade_restores_spans(self, tmp_path):
        # shared in-process store, sample_every > 1: the replica's finish
        # samples the trace OUT (spans cleared, sids tombstoned); the
        # router then merges the in-band copy and flags it.  The upgrade
        # must restore the spans (without re-counting aggregates), move
        # the kept/dropped counters, and re-emit the newest jsonl line
        # with the full end-to-end record.
        s = RequestTraceStore(sample_every=1000,
                              jsonl_path=str(tmp_path / "traces.jsonl"))
        s.finish("0" * 32)                    # burn the 1-in-N keep slot
        tid = "a" * 32
        s.add_span(tid, "prefill", t0=1.0, dur_s=1.0, component="serve:1")
        rep = s.finish(tid, wall_s=1.0)       # replica hop: sampled out
        assert s.get(tid) is None
        assert s.merge(tid, {"spans": rep["spans"],
                             "flags": rep["flags"]}) == 1
        s.add_span(tid, "route", t0=0.5, dur_s=2.0, component="router")
        s.flag(tid, "rerouted")
        s.finish(tid, wall_s=2.0)             # router hop: keep-upgrade
        assert sorted(sp["kind"] for sp in s.get(tid)["spans"]) \
            == ["prefill", "route"]
        assert s.counters["trace/dropped"] == 0
        assert s.counters["trace/kept"] == 2
        assert s.segment_summary()["prefill"]["count"] == 1
        s.flush()
        from deepspeed_tpu.telemetry.tracing.cli import load_traces

        (rec,) = [r for r in load_traces(str(tmp_path))
                  if r["trace"] == tid]
        assert sorted(sp["kind"] for sp in rec["spans"]) \
            == ["prefill", "route"]
        assert rec["wall_s"] == 2.0

    def test_ring_bounded(self):
        s = RequestTraceStore(sample_every=1, max_traces=8)
        for i in range(50):
            tid = f"{i:032x}"
            s.add_span(tid, "route", t0=0.0, dur_s=0.0)
            s.finish(tid, wall_s=0.01)
        assert len(s.traces()) <= 8
        assert s.counters["trace/evicted"] >= 42


# --------------------------------------------------------------------- #
# Scheduler span production (one shared engine)
# --------------------------------------------------------------------- #
class TestSchedulerSpans:
    def test_full_lifecycle_span_taxonomy(self, shared_eng, fresh_store):
        s = LifecycleScheduler(shared_eng, window_steps=4)
        ctx = TraceContext.mint()
        t0 = time.time()
        s.submit(ServeRequest(uid=1, prompt=[4, 6, 8], max_new_tokens=12,
                              trace=ctx))
        s.run_until_idle()
        t1 = time.time()
        rec = s.request(1).trace_result
        assert rec is not None and rec["kept"]
        kinds = {sp["kind"] for sp in rec["spans"]}
        assert {"queue_wait", "admission", "prefill"} <= kinds
        assert "decode_window" in kinds or "compile" in kinds
        # every span names this scheduler's component and the uid
        assert {sp["component"] for sp in rec["spans"]} == {"serve"}
        assert {sp["uid"] for sp in rec["spans"]} == {1}
        # the typed segments account for (nearly all of) the request wall
        assert span_coverage(rec["spans"], t0, t1) >= 0.8
        assert fresh_store.segment_summary()["prefill"]["count"] >= 1

    def test_untraced_request_records_nothing(self, shared_eng,
                                              fresh_store):
        s = LifecycleScheduler(shared_eng, window_steps=4)
        s.submit(ServeRequest(uid=2, prompt=[4, 6], max_new_tokens=4))
        s.run_until_idle()
        assert s.request(2).trace_result is None
        assert fresh_store.counters.get("trace/started", 0) == 0

    def test_expiry_incident_names_trace_and_flags(self, shared_eng,
                                                   tmp_path):
        from deepspeed_tpu.telemetry import Telemetry, set_telemetry

        tel = Telemetry(output_dir=str(tmp_path / "tel"))
        set_telemetry(tel)
        try:
            clock = FakeClock()
            s = LifecycleScheduler(shared_eng, clock=clock)
            ctx = TraceContext.mint()
            s.submit(ServeRequest(uid=3, prompt=[3, 5], max_new_tokens=4,
                                  deadline_s=2.0, trace=ctx))
            clock.advance(5.0)
            s.step()
            assert s.request(3).state == RequestState.EXPIRED
            events = tel.events.recent(kind="serving_expired")
            assert events and events[-1]["trace"] == ctx.trace_id
            rec = s.request(3).trace_result
            assert rec["kept"] and "deadline_expired" in rec["flags"]
        finally:
            set_telemetry(None)
            tel.close()

    def test_speculative_stream_has_draft_and_verify_spans(
            self, shared_eng):
        from deepspeed_tpu.inference.v2.speculative import (
            NGramDrafter,
            SpeculativeConfig,
        )

        s = LifecycleScheduler(
            shared_eng, window_steps=4,
            speculative=SpeculativeConfig(mode="ngram", k=4),
            drafter=NGramDrafter())
        ctx = TraceContext.mint()
        s.submit(ServeRequest(uid=4, prompt=[142] * 6, max_new_tokens=10,
                              trace=ctx))
        s.run_until_idle()
        rec = s.request(4).trace_result
        kinds = {sp["kind"] for sp in rec["spans"]}
        assert "draft" in kinds
        assert "verify" in kinds or "compile" in kinds


class TestPreemptResumeTrace:
    def test_preempted_stream_trace_shows_both_lives(self, tiny_lm):
        """Propagation through preemption/resume: the victim's ONE trace
        carries its first admission, the preempt marker, a SECOND
        queue_wait + resume, and lands flagged (always-kept)."""
        eng = _engine(tiny_lm, max_tokens=16, num_blocks=10)
        s = LifecycleScheduler(eng, window_steps=4, kv_high_watermark=0.2)
        ctx = TraceContext.mint()
        s.submit(ServeRequest(uid=0, prompt=[3, 5, 7, 11, 13],
                              max_new_tokens=16, trace=ctx))
        s.step()
        s.step()                    # uid 0 decoding, holds 3 of 10 blocks
        s.submit(ServeRequest(uid=1, prompt=[2] * 40, max_new_tokens=24))
        s.run_until_idle()
        assert s.counters["serving/preempted"] == 1
        rec = s.request(0).trace_result
        kinds = [sp["kind"] for sp in rec["spans"]]
        assert "preempt" in kinds and "resume" in kinds
        assert kinds.count("queue_wait") == 2   # admitted twice
        assert "preempted" in rec["flags"] and rec["kept"]


# --------------------------------------------------------------------- #
# Fleet: merged disagg trace + reroute across replica death
# --------------------------------------------------------------------- #
def _mk_replica(tiny_lm, block_size=8):
    from deepspeed_tpu.inference.v2.server import ServingServer

    eng = _engine(tiny_lm, block_size=block_size, max_ctx=96)
    sched = LifecycleScheduler(eng, window_steps=4, max_queue=16)
    return eng, sched, ServingServer(sched, port=0,
                                     bind="127.0.0.1").start()


def _post(port, body, timeout=300, path="/v1/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestFleetMergedTrace:
    def test_disagg_request_one_merged_trace_covers_wall(self, tiny_lm,
                                                         fresh_store):
        """THE acceptance property: router → prefill replica → KV ship →
        decode replica produces ONE merged trace whose typed work
        segments cover ≥90% of the externally measured request wall."""
        from deepspeed_tpu.serving.fleet import FleetRouter, RouterServer

        _, _, rd = _mk_replica(tiny_lm, block_size=8)
        _, _, rp = _mk_replica(tiny_lm, block_size=16)
        router = FleetRouter(poll_s=0.2, disagg_threshold=8)
        router.add_replica(f"127.0.0.1:{rd.port}", role="decode")
        router.add_replica(f"127.0.0.1:{rp.port}", role="prefill")
        rs = RouterServer(router, port=0, bind="127.0.0.1").start()
        try:
            prompt = [3, 5, 7, 11, 13, 17, 19, 23, 29, 31]
            t0 = time.time()
            code, out = _post(rs.port, {"prompt": prompt,
                                        "max_new_tokens": 12})
            t1 = time.time()
            assert code == 200 and out["state"] == "finished"
            tid = out["trace_id"]
            rec = fresh_store.get(tid)
            assert rec is not None
            kinds = {sp["kind"] for sp in rec["spans"]}
            comps = {sp["component"] for sp in rec["spans"]}
            # the disaggregated path end to end, in one trace
            assert {"queue_wait", "admission", "prefill",
                    "kv_ship_encode", "kv_ship_wire",
                    "kv_ship_import", "route"} <= kinds
            assert comps == {"router", f"serve:{rd.port}",
                             f"serve:{rp.port}"}
            assert router.counters["fleet/prefill_disagg"] == 1
            # ≥90% of the measured wall is attributed to WORK segments
            # (the route envelope is excluded from the union)
            assert span_coverage(rec["spans"], t0, t1) >= 0.9
            # live endpoints resolve the id
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rs.port}/traces?request={tid}",
                    timeout=30) as r:
                assert json.loads(r.read())["trace"] == tid
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rs.port}/traces", timeout=30) as r:
                summary = json.loads(r.read())
            assert "prefill" in summary["segments"]
            assert summary["counters"].get("trace/kept", 0) >= 1
        finally:
            rs.stop()
            rd.stop()
            rp.stop()

    def test_rerouted_stream_merges_spans_from_both_replicas(self,
                                                             tiny_lm,
                                                             fresh_store):
        """Kill-mid-run chaos path: a replica dies after ADMITTING a
        stream but before its first token — the router reroutes, and the
        merged trace shows spans from BOTH replicas plus the reroute
        marker, flagged rerouted (always kept)."""
        from deepspeed_tpu.serving.fleet import FleetRouter, RouterServer

        _, _, r_dead = _mk_replica(tiny_lm)
        _, _, r_alive = _mk_replica(tiny_lm)
        router = FleetRouter(poll_s=30.0)      # no scrape rescue
        dead = router.add_replica(f"127.0.0.1:{r_dead.port}", name="dead")
        alive = router.add_replica(f"127.0.0.1:{r_alive.port}",
                                   name="alive")
        rs = RouterServer(router, port=0, bind="127.0.0.1").start()
        try:
            alive.queue_depth = 10             # bias the pick to 'dead'
            ctx = TraceContext.mint()
            done = {}

            def client():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{rs.port}/v1/generate",
                    data=json.dumps({
                        "prompt": [5, 6, 7], "max_new_tokens": 6,
                        "stream": True,
                        "traceparent": ctx.header()}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=300) as r:
                    done["body"] = r.read().decode()

            t = threading.Thread(target=client, daemon=True)
            t.start()
            # deterministic kill point: wait until the dead replica has
            # ADMITTED the stream (its queue_wait span is in the shared
            # store) — it is then mid-prefill-compile, zero tokens out
            deadline = time.time() + 60
            dead_comp = f"serve:{r_dead.port}"
            while time.time() < deadline:
                rec = fresh_store.get(ctx.trace_id)
                if rec and any(sp["component"] == dead_comp
                               for sp in rec["spans"]):
                    break
                time.sleep(0.01)
            r_dead.hard_kill()
            t.join(timeout=300)
            assert "finished" in done.get("body", "")
            assert router.counters["fleet/rerouted"] >= 1
            rec = fresh_store.get(ctx.trace_id)
            comps = {sp["component"] for sp in rec["spans"]}
            kinds = {sp["kind"] for sp in rec["spans"]}
            assert {dead_comp, f"serve:{r_alive.port}"} <= comps
            assert "reroute" in kinds
            assert "rerouted" in rec["flags"] and rec["kept"]
        finally:
            rs.stop()
            r_alive.stop()


# --------------------------------------------------------------------- #
# CLI + summary section (synthetic traces; no engines)
# --------------------------------------------------------------------- #
def _synthetic_store(tmp_path, n=3):
    store = RequestTraceStore(
        jsonl_path=str(tmp_path / "traces.jsonl"), sample_every=1)
    now = time.time()
    for i in range(n):
        tid = f"{i:032x}"
        store.add_span(tid, "queue_wait", t0=now, dur_s=0.01,
                       component="router", uid=i)
        store.add_span(tid, "prefill", t0=now + 0.01, dur_s=0.2 + i,
                       component="serve:1", uid=i, tokens=8)
        store.add_span(tid, "decode_window", t0=now + 0.3, dur_s=0.05,
                       component="serve:1", uid=i)
        store.finish(tid, wall_s=0.3 + i)
    return store


class TestTraceCLI:
    def test_overview_slowest_and_request_views(self, tmp_path, capsys):
        from deepspeed_tpu.telemetry.tracing.cli import main

        _synthetic_store(tmp_path)
        assert main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "per-segment decomposition" in out and "prefill" in out
        assert main([str(tmp_path), "--slowest", "2"]) == 0
        out = capsys.readouterr().out
        assert f"{2:032x}" in out            # the slowest (wall 2.3s)
        assert main([str(tmp_path), "--request", f"{1:032x}"]) == 0
        out = capsys.readouterr().out
        assert "queue_wait" in out and "decode_window" in out
        assert "coverage" in out

    def test_unknown_request_and_empty_dir(self, tmp_path, capsys):
        from deepspeed_tpu.telemetry.tracing.cli import main

        assert main([str(tmp_path)]) == 2    # no traces.jsonl yet
        capsys.readouterr()
        _synthetic_store(tmp_path)
        assert main([str(tmp_path), "--request", "ffff"]) == 1

    def test_chrome_export_reuses_span_exporter(self, tmp_path):
        from deepspeed_tpu.telemetry.tracing.cli import main

        _synthetic_store(tmp_path)
        out_json = str(tmp_path / "chrome.json")
        assert main([str(tmp_path), "--chrome", out_json]) == 0
        with open(out_json) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert evs and all(e["ph"] == "X" for e in evs)
        # components map to stable tids; every event names its trace
        assert {e["args"]["component"] for e in evs} == \
            {"router", "serve:1"}
        assert all("trace" in e["args"] for e in evs)


class TestTelemetrySection:
    def test_summary_renders_segments_and_exemplars(self, tmp_path):
        from deepspeed_tpu.telemetry import Telemetry, set_telemetry
        from deepspeed_tpu.telemetry.summary import (
            format_summary,
            summarize_run,
        )

        tel = Telemetry(output_dir=str(tmp_path / "tel"))
        set_telemetry(tel)
        try:
            store = RequestTraceStore(sample_every=1)
            install_trace_store(store)
            tid = "e" * 32
            store.add_span(tid, "prefill", t0=time.time(), dur_s=0.25)
            store.add_span(tid, "decode_window", t0=time.time(),
                           dur_s=0.03)
            store.note_exemplar("ttft_s", 0.8, tid)
            store.finish(tid, wall_s=0.3)
            tel.flush()
        finally:
            set_telemetry(None)
            tel.close()
        summary = summarize_run(str(tmp_path / "tel" / "events.jsonl"))
        tr = summary["tracing"]
        assert tr["segments"]["prefill"]["count"] == 1
        assert tr["counters"]["kept"] == 1
        assert tr["exemplars"]["ttft_s"][0]["trace"] == tid
        text = format_summary(summary)
        assert "request tracing" in text
        assert "TTFT tail exemplars" in text and tid[:12] in text


class TestHotPathCleanliness:
    def test_trace_bookkeeping_passes_host_sync_lint(self):
        """The dstpu-check source passes stay clean over the tracing
        plane and the instrumented decode hot path — span recording must
        never add a per-iteration device→host sync."""
        import os

        from deepspeed_tpu.analysis.source_passes import run_source_passes

        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        findings = run_source_passes([
            os.path.join(root, "deepspeed_tpu/telemetry/tracing"),
            os.path.join(root, "deepspeed_tpu/inference/v2/lifecycle.py"),
            os.path.join(root, "deepspeed_tpu/serving/fleet"),
        ])
        assert not findings, [f.render() for f in findings]
