"""CI gate for the serving smoke check (tools/check_serving_smoke.py):
`InferenceEngineV2` prefill → fused 4-token decode under both attention
impls, the request-lifecycle scenario (deadline expiry mid-window with
block reclaim + unperturbed survivor stream), the speculative-decoding
scenario (planted-repetition prompt → n-gram drafter accepts >=1
multi-token verify window → stream bit-identical to vanilla → blocks
reclaimed, both impls), the real `dstpu-serve` graceful-drain scenario
(SIGTERM during active decode → draining healthz → 503 for new work →
completed in-flight response → exit 0), and the FLEET scenario (real
`dstpu-router` over two `--prefix-cache` replicas: prefix-cached request
pair answers bit-identically to the cold replica with a counted cache
hit; SIGTERM-draining one replica loses zero streams and exits 0), and
the TRACE scenario (real disaggregated router: one request produces ONE
merged trace with queue/prefill/kv_ship/decode segments from both
replicas, resolvable via /traces and rendered by dstpu-trace) — all
on the CPU sim, same enforcement pattern as the no-bare-print lint, so
the serving stack cannot rot silently while the TPU relay is down."""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.serving

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CHECK = os.path.join(REPO_ROOT, "tools", "check_serving_smoke.py")


class TestServingSmoke:
    def test_smoke_check_passes(self):
        """This IS the CI gate: every scenario (decode parity + roofline,
        lifecycle expiry/reclaim, spec-dec bit-exactness + acceptance,
        dstpu-serve drain, fleet router + prefix-cache + replica drain,
        disaggregated request tracing) must hold."""
        proc = subprocess.run([sys.executable, CHECK],
                              capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0, \
            f"serving smoke checks failed:\n{proc.stdout}" \
            f"{proc.stderr[-1000:]}"
