"""CI gate for the serving engine smoke check
(tools/check_serving_smoke.py): `InferenceEngineV2` prefill → fused
4-token decode on the CPU sim under both attention impls — same
enforcement pattern as the no-bare-print lint, so the engine cannot rot
silently while the TPU relay is down."""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.serving

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CHECK = os.path.join(REPO_ROOT, "tools", "check_serving_smoke.py")


class TestServingSmoke:
    def test_smoke_check_passes(self):
        """This IS the CI gate: prefill→decode must work under both attn
        impls, agree on the greedy stream, and record the decode roofline."""
        proc = subprocess.run([sys.executable, CHECK],
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, \
            f"serving smoke checks failed:\n{proc.stdout}" \
            f"{proc.stderr[-1000:]}"
