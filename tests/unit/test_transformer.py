"""Flagship model tests: e2e training, TP equivalence, remat."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.runtime.topology import TopologyConfig, initialize_mesh

pytestmark = pytest.mark.core


def tiny_batch(batch=8, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": jnp.asarray(
        rng.integers(0, vocab, size=(batch, seq)), jnp.int32)}


def build(topo_cfg=TopologyConfig(), zero_stage=0, remat=False, micro=1, seed=0):
    topo = initialize_mesh(topo_cfg, force=True)
    model = CausalLM(TransformerConfig.tiny(remat=remat, use_flash=False))
    params = model.init_params(jax.random.PRNGKey(seed))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": zero_stage}},
        topology=topo)
    return engine


class TestCausalLM:
    def test_forward_shapes(self):
        model = CausalLM(TransformerConfig.tiny(use_flash=False))
        params = model.init_params(jax.random.PRNGKey(0))
        logits = model(params, tiny_batch()["input_ids"])
        assert logits.shape == (8, 32, 256)

    @pytest.mark.slow

    def test_train_loss_decreases(self):
        engine = build()
        batch = tiny_batch(engine.train_batch_size())
        losses = [float(engine.train_batch(batch)) for _ in range(10)]
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    @pytest.mark.slow

    def test_tp_matches_dp(self):
        """TP=2 mesh must produce the same loss trajectory as pure DP."""
        e_dp = build(TopologyConfig())
        e_tp = build(TopologyConfig(tensor=2))
        batch = tiny_batch(e_dp.train_batch_size())
        tp_batch = tiny_batch(e_tp.train_batch_size())
        l_dp = [float(e_dp.train_batch(batch)) for _ in range(3)]
        l_tp = [float(e_tp.train_batch(tp_batch)) for _ in range(3)]
        # same data prefix (tp batch is half the rows of dp batch) → compare
        # instead with identical global batch: rebuild dp engine at micro=0.5 not
        # possible; so just check TP runs and loss is finite + decreasing
        assert l_tp[-1] < l_tp[0]

    @pytest.mark.slow

    def test_tp_numerics_match_exactly(self):
        """Same global batch under TP=2 vs DP-only: losses must agree."""
        e_dp = build(TopologyConfig(), micro=2)          # dp=8  → global 16
        e_tp = build(TopologyConfig(tensor=2), micro=4)  # dp=4  → global 16
        batch = tiny_batch(16)
        for _ in range(2):
            l_dp = float(e_dp.train_batch(batch))
            l_tp = float(e_tp.train_batch(batch))
        np.testing.assert_allclose(l_dp, l_tp, rtol=1e-4)

    @pytest.mark.slow

    def test_zero3_with_tp(self):
        engine = build(TopologyConfig(tensor=2), zero_stage=3)
        batch = tiny_batch(engine.train_batch_size())
        l0 = float(engine.train_batch(batch))
        l5 = None
        for _ in range(5):
            l5 = float(engine.train_batch(batch))
        assert l5 < l0

    def test_remat(self):
        engine = build(remat=True)
        batch = tiny_batch(engine.train_batch_size())
        assert np.isfinite(float(engine.train_batch(batch)))

    def test_seq_parallel_runs(self):
        engine = build(TopologyConfig(seq=2))
        batch = tiny_batch(engine.train_batch_size())
        l0 = float(engine.train_batch(batch))
        assert np.isfinite(l0)

    def test_num_params_and_flops(self):
        model = CausalLM(TransformerConfig.tiny())
        assert model.num_params() > 0
        assert model.flops_per_token() > 0
