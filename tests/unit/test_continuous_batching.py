"""Continuous-batching scheduler at the operating point (VERDICT r3 #7):
64-sequence churn (admission, eviction, block recycling) and O(batch)
scheduling cost independent of queue depth.

Reference analogue: the MII scheduling layer over
deepspeed/inference/v2/engine_v2.py:158-242 budget primitives.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (
    ContinuousBatcher,
    InferenceEngineV2,
    RaggedInferenceEngineConfig,
    SchedulingResult,
)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.runtime.topology import TopologyConfig, initialize_mesh

pytestmark = pytest.mark.inference


@pytest.fixture(scope="module")
def tiny():
    initialize_mesh(TopologyConfig(), force=True)
    cfg = TransformerConfig.tiny(use_flash=False)
    model = CausalLM(cfg)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    defaults = dict(max_tokens=16, max_seqs=4, max_ctx=64, block_size=8,
                    dtype=jnp.float32, attn_impl="gather")
    defaults.update(kw)
    return InferenceEngineV2(model, params,
                             RaggedInferenceEngineConfig(**defaults))


class TestChurn:
    def test_64_stream_churn_with_tight_kv(self, tiny):
        """64 staggered requests through a cache that holds only ~4 live
        sequences: the batcher must admit in waves, evict at completion,
        recycle every block, and complete ALL streams."""
        model, params = tiny
        # 16 blocks x 8 = 128 slots; each request reserves
        # ceil((prompt + max_new)/8) blocks -> ~3-4 concurrent residents
        eng = _engine(model, params, num_blocks=16)
        b = ContinuousBatcher(eng, max_new_tokens=6)
        rng = np.random.default_rng(0)
        for u in range(64):
            b.add_request(u, rng.integers(1, 255, size=int(rng.integers(
                3, 20))).tolist())
        steps = 0
        while b.pending:
            b.step()
            steps += 1
            assert steps < 2000, "churn did not converge"
        assert len(b.finished) == 64
        assert all(len(v) == 6 for v in b.finished.values())
        # every block back in the pool; no tracked-sequence leak
        assert eng.state_manager.free_blocks == 16
        assert eng.state_manager.n_tracked_sequences == 0

    def test_matches_generate_output(self, tiny):
        """Batcher-driven serving produces the same greedy tokens as the
        one-shot generate loop (same engine semantics underneath)."""
        model, params = tiny
        prompts = [[3, 5, 7, 11, 13], [17, 19], [23, 29, 31]]
        eng1 = _engine(model, params)
        ref = eng1.generate(prompts, max_new_tokens=8)
        eng2 = _engine(model, params)
        b = ContinuousBatcher(eng2, max_new_tokens=8)
        for u, p in enumerate(prompts):
            b.add_request(u, p)
        out = b.run()
        assert [out[u] for u in range(3)] == ref

    def test_eos_and_rejection(self, tiny):
        model, params = tiny
        eng = _engine(model, params)
        b = ContinuousBatcher(eng, max_new_tokens=8, eos_token_id=1)
        b.add_request(0, [3, 5])
        b.add_request(1, list(range(1, 200)))     # > max_ctx: rejected
        b.add_request(2, [])                      # empty: finished at once
        out = b.run()
        assert out[1] == [] and out[2] == []
        assert 1 <= len(out[0]) <= 8
        assert eng.state_manager.free_blocks == eng.kv.config.num_blocks


class TestSchedulingCost:
    def test_next_batch_touch_count_independent_of_queue_depth(self, tiny):
        """Scheduling examines O(batch) uids regardless of how many requests
        are queued — the kill-the-rescan criterion, pinned structurally
        (touched-uid count), not by wall clock."""
        model, params = tiny
        touched = {}
        for depth in (100, 5000):
            eng = _engine(model, params, num_blocks=16)
            b = ContinuousBatcher(eng, max_new_tokens=4)
            for u in range(depth):
                b.add_request(u, [3, 5, 7])
            b.step()
            touched[depth] = b.touched
        assert touched[5000] == touched[100], touched
        assert touched[5000] <= 4 + 4      # max_seqs decodes + admissions

    def test_steady_state_touch_bound(self, tiny):
        """Mid-churn (mixed decodes + prefills + deep queue) the per-step
        touch count stays within the batch budget bound."""
        model, params = tiny
        eng = _engine(model, params, num_blocks=16)
        b = ContinuousBatcher(eng, max_new_tokens=4)
        for u in range(500):
            b.add_request(u, [3, 5, 7, 11, 13])
        cap = eng.config.max_seqs * 2 + 1
        for _ in range(25):
            if not b.pending:
                break
            b.step()
            assert b.touched <= cap, (b.touched, cap)


class TestEvictionEdgeCases:
    """Scheduler eviction paths that existed untested: flushing a uid whose
    async DecodeWindow has not been drained yet, and admission of a request
    whose whole-lifetime block reservation can never fit the pool."""

    def test_flush_of_uid_inside_undrained_window(self, tiny):
        """flush() while the uid's fused window is still in flight: the
        window must still drain cleanly, the blocks must be back in the
        pool immediately, the engine's device-resume state must be
        invalidated (a later window repacks instead of resuming the
        flushed stream), and the freed blocks must be re-admittable."""
        model, params = tiny
        eng = _engine(model, params, num_blocks=6)
        logits = eng.put([0], [[3, 5, 7, 11]])
        seed = int(jnp.argmax(logits[0]))
        window = eng.decode_batch_async([0], [seed], steps=4)
        eng.flush([0])                          # mid-flight eviction
        assert eng.state_manager.free_blocks == 6
        assert eng._decode_state is None        # resume state invalidated
        toks = window.tokens()                  # drains without error
        assert toks.shape == (4, 1)
        assert window.nonfinite is not None and not window.nonfinite.any()
        # freed blocks are re-admittable: a new request prefills + decodes
        logits = eng.put([1], [[2] * 14])
        seed = int(jnp.argmax(logits[0]))
        toks2 = eng.decode_batch([1], [seed], steps=4)
        assert toks2.shape == (4, 1)
        # the flushed uid's stale stream was NOT resumed into uid 1
        assert eng.decode_resume_hits == 0
        eng.flush([1])
        assert eng.state_manager.free_blocks == 6

    def test_whole_lifetime_reservation_exceeding_pool_rejects(self, tiny):
        """A request whose prompt+decode reservation exceeds the pool must
        be rejected at admission — NOT hold the queue head hostage while
        the allocator waits for blocks that can never exist."""
        model, params = tiny
        eng = _engine(model, params, num_blocks=4)   # 32-token pool
        assert eng.can_schedule([0], [40]) is not SchedulingResult.Success
        b = ContinuousBatcher(eng, max_new_tokens=16)
        b.add_request(0, [2] * 30)          # 30+16 = 46 tokens > pool
        b.add_request(1, [3, 5, 7])         # fits easily behind it
        done = b.run()
        assert b.rejected == [0]
        assert done[0] == []                # rejected, empty stream
        assert len(done[1]) == 16           # the head never wedged
        assert eng.state_manager.free_blocks == 4
