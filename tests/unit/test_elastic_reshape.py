"""Elastic resharding: the agent re-plans a gang to the visible capacity
(--allow-reshape), exports the mesh shape to workers, and the live plane
reports the reshaped gang as degraded{reason="reshaped"}."""
import os
import sys

import pytest

from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
from deepspeed_tpu.runtime.fault import injection
from deepspeed_tpu.runtime.fault.retry import (RetryPolicy, fault_counters,
                                               reset_fault_counters)
from deepspeed_tpu.runtime.topology import (TopologyConfig, mesh_shape_str,
                                            parse_mesh_shape,
                                            topology_config_from_env)

pytestmark = pytest.mark.elastic

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FAST_RESTART = RetryPolicy(max_retries=10, base_s=0.01, cap_s=0.02, jitter=0.0)


@pytest.fixture(autouse=True)
def _reset_fault_state():
    injection.clear()
    reset_fault_counters()
    yield
    injection.clear()
    reset_fault_counters()


def agent_env(**extra):
    env = {"PATH": os.environ.get("PATH", ""), "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO_ROOT, "HOME": os.environ.get("HOME", "/tmp")}
    env.update(extra)
    return env


FAIL_ONCE_THEN_DUMP_ENV = (
    "import os, sys\n"
    "log = os.environ['WORKER_LOG']\n"
    "with open(log, 'a') as f:\n"
    "    f.write('%s %s %s %s\\n' % ("
    "os.environ['WORLD_SIZE'], os.environ['RANK'],"
    "os.environ.get('DSTPU_ELASTIC_MESH_SHAPE', '-'),"
    "os.environ.get('DSTPU_ELASTIC_RESHAPE_COUNT', '-')))\n"
    "sys.exit(1 if os.environ['DSTPU_ELASTIC_RESTART_COUNT'] == '0' else 0)\n"
)


class TestMeshShapeWire:
    def test_roundtrip(self):
        cfg = parse_mesh_shape("data:4,tensor:2")
        assert cfg.data == 4 and cfg.tensor == 2
        dims = cfg.resolve(8)
        assert mesh_shape_str(dims) == "data:4,tensor:2"

    def test_bare_world_size(self):
        assert parse_mesh_shape("6").data == 6

    def test_mics_mesh_roundtrips_via_zero_shard(self):
        """data_outer (MiCS replica groups) has no TopologyConfig field of
        its own — the wire format spells it data:<full>,zero_shard:<inner>
        and must parse back to the identical mesh."""
        cfg = TopologyConfig(data=8, zero_shard_size=4)
        dims = cfg.resolve(8)
        assert dims["data_outer"] == 2 and dims["data"] == 4
        wire = mesh_shape_str(dims)
        assert wire == "data:8,zero_shard:4"
        assert parse_mesh_shape(wire).resolve(8) == dims

    def test_trivial_mesh_renders_world_on_data(self):
        assert mesh_shape_str({"pipe": 1, "data": 1, "tensor": 1}) == "data:1"

    def test_unknown_axis_rejected(self):
        with pytest.raises(ValueError, match="unknown mesh axis"):
            parse_mesh_shape("bogus:4")

    def test_env_reader(self, monkeypatch):
        monkeypatch.delenv("DSTPU_ELASTIC_MESH_SHAPE", raising=False)
        assert topology_config_from_env() is None
        monkeypatch.setenv("DSTPU_ELASTIC_MESH_SHAPE", "data:2,tensor:2")
        cfg = topology_config_from_env()
        assert isinstance(cfg, TopologyConfig)
        assert cfg.resolve(4) == {"pipe": 1, "data_outer": 1, "data": 2,
                                  "expert": 1, "seq": 1, "tensor": 2}


class TestAgentReshape:
    def run_agent(self, tmp_path, allow_reshape, probe):
        log = tmp_path / "workers.log"
        agent = DSElasticAgent(
            [sys.executable, "-c", FAIL_ONCE_THEN_DUMP_ENV],
            world_size=4, max_restarts=3, monitor_interval=0.02,
            env=agent_env(WORKER_LOG=str(log)), term_timeout=0.5,
            restart_policy=FAST_RESTART, allow_reshape=allow_reshape,
            capacity_probe=probe)
        rc = agent.run()
        lines = [ln.split() for ln in log.read_text().splitlines()]
        return agent, rc, lines

    def test_reshape_shrinks_gang_and_exports_mesh_shape(self, tmp_path):
        agent, rc, lines = self.run_agent(tmp_path, True, lambda: 2)
        assert rc == 0
        assert agent.reshape_count == 1
        assert agent.world_size == 2
        assert agent.current_mesh_shape == "data:2"
        # first incarnation: world 4, no mesh-shape override.  The agent
        # tears the gang down as soon as ONE worker fails, so slower
        # workers may never reach their log line — assert on whoever did.
        first = [ln for ln in lines if ln[0] == "4"]
        assert first and all(ln[2] == "-" and ln[3] == "0" for ln in first)
        # restarted incarnation: 2 workers, reshaped env visible
        second = [ln for ln in lines if ln[0] == "2"]
        assert len(second) == 2
        assert all(ln[2] == "data:2" and ln[3] == "1" for ln in second)
        assert fault_counters()["elastic/reshapes"] == 1

    def test_capacity_restored_clears_mesh_shape(self, tmp_path):
        """Growing back to the launch-time capacity clears the reshaped
        breadcrumb: the gang is whole again, not degraded."""
        answers = iter([2, 4, 4, 4])
        script = (
            "import os, sys\n"
            "log = os.environ['WORKER_LOG']\n"
            "with open(log, 'a') as f:\n"
            "    f.write('%s %s\\n' % (os.environ['WORLD_SIZE'],"
            "os.environ.get('DSTPU_ELASTIC_MESH_SHAPE', '-')))\n"
            "sys.exit(1 if int(os.environ['DSTPU_ELASTIC_RESTART_COUNT']) < 2"
            " else 0)\n")
        log = tmp_path / "w.log"
        agent = DSElasticAgent(
            [sys.executable, "-c", script], world_size=4, max_restarts=4,
            monitor_interval=0.02, env=agent_env(WORKER_LOG=str(log)),
            term_timeout=0.5, restart_policy=FAST_RESTART,
            allow_reshape=True, capacity_probe=lambda: next(answers))
        assert agent.run() == 0
        assert agent.reshape_count == 2        # 4→2, then 2→4
        assert agent.current_mesh_shape is None
        final = [ln for ln in log.read_text().splitlines()
                 if ln.startswith("4 ")]
        assert any(ln.endswith(" -") for ln in final)

    def test_without_allow_reshape_capacity_is_ignored(self, tmp_path):
        agent, rc, lines = self.run_agent(tmp_path, False, lambda: 2)
        assert rc == 0
        assert agent.reshape_count == 0 and agent.world_size == 4
        assert all(ln[0] == "4" for ln in lines)

    def test_broken_probe_never_blocks_restart(self, tmp_path):
        def probe():
            raise RuntimeError("resource manager down")

        agent, rc, lines = self.run_agent(tmp_path, True, probe)
        assert rc == 0
        assert agent.reshape_count == 0 and agent.world_size == 4


class TestInitializeHonorsReshapedEnv:
    def test_initialize_builds_env_mesh_over_config(self, monkeypatch):
        """A worker restarted by a reshaping agent must get the re-planned
        mesh from deepspeed_tpu.initialize() itself — the DeepSpeed config
        still describes the stale launch-time world."""
        import jax

        import deepspeed_tpu

        from .simple_model import init_mlp_params, mlp_loss_fn

        monkeypatch.setenv("DSTPU_ELASTIC_MESH_SHAPE", "data:4")
        config = {"train_micro_batch_size_per_gpu": 2,
                  "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                  "zero_optimization": {"stage": 1},
                  "bf16": {"enabled": False}}
        params = init_mlp_params(jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=mlp_loss_fn, model_parameters=params, config=config)
        # 8 visible sim devices, but the gang was re-planned to 4
        assert engine.topology.world_size() == 4
        assert engine.topology.dims["data"] == 4

    def test_explicit_topology_still_wins(self, monkeypatch):
        import jax

        import deepspeed_tpu
        from deepspeed_tpu.runtime.topology import (TopologyConfig,
                                                    initialize_mesh)

        from .simple_model import init_mlp_params, mlp_loss_fn

        monkeypatch.setenv("DSTPU_ELASTIC_MESH_SHAPE", "data:4")
        topo = initialize_mesh(TopologyConfig(), force=True)   # 8-dev
        params = init_mlp_params(jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=mlp_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "bf16": {"enabled": False}},
            topology=topo)
        assert engine.topology.world_size() == 8


class TestHealthzReshaped:
    def test_reshaped_env_reports_degraded(self, monkeypatch, tmp_path):
        from deepspeed_tpu.telemetry import Telemetry
        from deepspeed_tpu.telemetry.live.server import (
            STATUS_DEGRADED, elastic_state_from_env, health_report,
            publish_elastic_gauges)

        monkeypatch.setenv("DSTPU_ELASTIC_RESTART_COUNT", "2")
        monkeypatch.setenv("DSTPU_ELASTIC_RESHAPE_COUNT", "1")
        monkeypatch.setenv("DSTPU_ELASTIC_MESH_SHAPE", "data:2")
        tel = Telemetry(output_dir=str(tmp_path), jsonl=False)
        state = elastic_state_from_env()
        assert state["reshaped"] and state["mesh_shape"] == "data:2"
        # past the recovering window, a reshaped gang is degraded
        report = health_report(tel, step_fn=lambda: 50,
                               steps_this_process_fn=lambda: 50)
        assert report["status"] == STATUS_DEGRADED
        assert any("reshaped" in r for r in report["reasons"])
        publish_elastic_gauges(tel.metrics)
        assert tel.metrics.gauge("elastic/reshape_count").value() == 1
        assert tel.metrics.gauge("elastic/degraded").value(
            reason="reshaped") == 1

    def test_recovering_takes_precedence_right_after_restart(self, monkeypatch, tmp_path):
        from deepspeed_tpu.telemetry import Telemetry
        from deepspeed_tpu.telemetry.live.server import (STATUS_RECOVERING,
                                                         health_report)

        monkeypatch.setenv("DSTPU_ELASTIC_RESTART_COUNT", "1")
        monkeypatch.setenv("DSTPU_ELASTIC_MESH_SHAPE", "data:2")
        report = health_report(Telemetry(output_dir=str(tmp_path),
                                          jsonl=False), step_fn=lambda: 1,
                               steps_this_process_fn=lambda: 0)
        assert report["status"] == STATUS_RECOVERING

    def test_unreshaped_gang_stays_healthy(self, monkeypatch, tmp_path):
        from deepspeed_tpu.telemetry import Telemetry
        from deepspeed_tpu.telemetry.live.server import (STATUS_HEALTHY,
                                                         health_report)

        monkeypatch.delenv("DSTPU_ELASTIC_MESH_SHAPE", raising=False)
        monkeypatch.setenv("DSTPU_ELASTIC_RESTART_COUNT", "1")
        monkeypatch.setenv("DSTPU_ELASTIC_RESHAPE_COUNT", "0")
        report = health_report(Telemetry(output_dir=str(tmp_path),
                                          jsonl=False), step_fn=lambda: 50,
                               steps_this_process_fn=lambda: 50)
        assert report["status"] == STATUS_HEALTHY
