"""Minimal end-to-end training example: a llama-family model through
``deepspeed_tpu.initialize`` with ZeRO-3, bf16, warmup LR, and checkpointing.

Runs on one TPU chip or on the CPU-sim mesh:

    # 8 simulated devices (no TPU needed)
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_llama.py

DeepSpeed users: the config dict below is DeepSpeed-JSON compatible — a
``ds_config.json`` loads unchanged via ``config="ds_config.json"``.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some containers register an accelerator plugin via sitecustomize BEFORE
# user code runs, capturing the platform choice; the explicit config update
# (not just the env var) is the authoritative override there.
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--save", type=str, default="")
    args = ap.parse_args()

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=args.hidden,
        intermediate_size=args.hidden * 11 // 4, num_layers=args.layers,
        num_heads=max(args.hidden // 64, 1),
        num_kv_heads=max(args.hidden // 128, 1),
        max_seq_len=args.seq, remat=True,
        use_flash=jax.default_backend() == "tpu")
    model = CausalLM(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    print(f"model: {model.num_params()/1e6:.1f}M params, "
          f"{len(jax.devices())} device(s)")

    engine, _, _, scheduler = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": args.batch,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_max_lr": 3e-4,
                                     "warmup_num_steps": 10}},
            "zero_optimization": {"stage": 3},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
        })

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size,
                         size=(engine.train_batch_size(), args.seq)),
            jnp.int32)}
        loss = engine.train_batch(batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f}")

    if args.save:
        engine.save_checkpoint(args.save, tag="final")
        print(f"checkpoint saved to {args.save}")


if __name__ == "__main__":
    main()
