"""Minimal serving example: continuous batching through the FastGen-style
ragged engine — paged KV cache, SplitFuse scheduling, fused decode windows.

    JAX_PLATFORMS=cpu python examples/serve_continuous_batching.py

For a real checkpoint, build the engine via ``deepspeed_tpu.init_inference``
(HF-style) instead; this example uses a random tiny model so it runs
anywhere.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some containers register an accelerator plugin via sitecustomize BEFORE
# user code runs, capturing the platform choice; the explicit config update
# (not just the env var) is the authoritative override there.
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax
import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import (
    ContinuousBatcher,
    InferenceEngineV2,
    RaggedInferenceEngineConfig,
)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.runtime.topology import TopologyConfig, initialize_mesh


def main():
    initialize_mesh(TopologyConfig(), force=True)
    cfg = TransformerConfig(
        vocab_size=1000, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
        use_flash=jax.default_backend() == "tpu")
    model = CausalLM(cfg)
    params = model.init_params(jax.random.PRNGKey(0))

    engine = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        max_tokens=64,          # SplitFuse token budget per forward
        max_seqs=8,             # live sequences per batch
        max_ctx=256,
        block_size=16,          # KV page size
        attn_impl="paged" if jax.default_backend() == "tpu" else "gather"))

    # --- one-shot batch API --------------------------------------------- #
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 1000, size=n).tolist() for n in (12, 5, 30)]
    outs = engine.generate(prompts, max_new_tokens=16)
    for i, o in enumerate(outs):
        print(f"request {i}: prompt {len(prompts[i])} tokens -> {o[:8]}...")

    # --- streaming/server-style API: requests arrive over time ---------- #
    batcher = ContinuousBatcher(engine, max_new_tokens=12)
    for uid in range(20):                       # 20 queued requests
        batcher.add_request(uid, rng.integers(1, 1000, size=8).tolist())
    steps = 0
    while batcher.pending:
        finished = batcher.step()               # one SplitFuse forward
        steps += 1
        for uid in finished:
            print(f"  step {steps}: request {uid} done "
                  f"({len(batcher.finished[uid])} tokens)")
    print(f"served 20 requests in {steps} engine steps "
          f"(KV blocks free again: {engine.state_manager.free_blocks})")


if __name__ == "__main__":
    main()
