"""Memory observability plane: raw sampling + the HBM occupancy ledger.

What memory "did" during a run is the question HBM-bound debugging always
starts with — and *whose* bytes they were is the question the memory-tiering
roadmap item (host-offload for optimizer state and cold KV) cannot be
designed without.  Two layers, one collection path:

**Raw totals** (:func:`collect_raw_totals`, PR-2 :class:`MemorySampler`):

  * ``jax.live_arrays()`` — every live jax.Array this process holds a
    reference to, summed into total bytes + count (catches Python-side
    leaks: a list someone keeps appending device arrays to);
  * ``device.memory_stats()`` — the runtime allocator's view
    (``bytes_in_use`` / ``peak_bytes_in_use``) where the backend provides
    it (TPU does; CPU may return None/{}).

**Occupancy ledger** (:class:`MemoryLedger`): attributes the live bytes to
a closed, non-overlapping bucket set (:data:`MEM_BUCKETS`) by asking
registered sources — the serving engine registers its params tree, the
WHOLE KV page pool (``jax.live_arrays`` sees the preallocated pool
regardless of allocation; the used/free/cold split lives in the heat
section), and its decode workspace; training engines register optimizer
state / gradient accumulators / LoCo residuals.  The conservation contract
mirrors the PR-17 goodput ledger: bytes the sources do not claim surface
as ``unattributed_bytes``, and the snapshot is ``conserved`` iff
``|unattributed| <= eps * live`` (eps = 2%).  Pre-existing process bytes
(JAX runtime constants, other components' arrays) are folded into
``other`` once via :meth:`MemoryLedger.capture_baseline`.

A crossing of the conservation bound emits a ``mem_unattributed`` incident
event (edge-triggered) and bumps the ``mem/unattributed`` counter — both
registered with the incident machinery (summary ``EVENT_KINDS_INCIDENT``,
live-aggregator ``INCIDENT_COUNTERS``).

Install pattern and fleet rollup mirror the goodput ledger: process-global
instance via :func:`install_memory_ledger` / :func:`get_memory_ledger`
(None IS the disabled fast path), replicas embed :meth:`snapshot` in their
``/healthz`` body and serve it at ``GET /memory``, and the router's
:func:`rollup` sums bucket bytes + KV heat across replicas into the fleet
view ``dstpu-mem`` and the future spill autotuner read.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, List, Optional

#: the closed bucket axis — non-overlapping by contract; every registered
#: source claims bytes in exactly one bucket
MEM_BUCKETS = ("params", "optimizer_state", "grad_acc", "kv_pages",
               "decode_workspace", "loco_residuals",
               "host_kv", "host_optimizer", "other")

#: buckets whose bytes live OUTSIDE ``jax.live_arrays`` (host-tier numpy
#: buffers) — reported and gauged like any bucket, but excluded from the
#: conservation sum, which judges device-side attribution only.  The
#: ``host_optimizer`` bucket stays IN conservation: twin-flow host halves
#: are jax arrays (pinned_host memory kind) on every backend.
NON_DEVICE_BUCKETS = ("host_kv",)

#: conservation bound: unattributed bytes beyond this fraction of live
#: bytes mean the ledger's sources have drifted from reality
CONSERVATION_EPS = 0.02


def collect_raw_totals() -> Dict[str, Any]:
    """One poll of both raw sources (live arrays + device allocator
    stats); keys are absent when a source is unavailable."""
    import jax

    out: Dict[str, Any] = {}
    try:
        live = jax.live_arrays()
        out["live_array_bytes"] = int(
            sum(getattr(a, "nbytes", 0) or 0 for a in live))
        out["live_array_count"] = len(live)
    except Exception:
        pass

    per_device = []
    try:
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            per_device.append({
                "device": str(d.id),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            })
    except Exception:
        pass
    if per_device:
        out["device_bytes_in_use"] = sum(
            d["bytes_in_use"] for d in per_device)
        out["device_peak_bytes_in_use"] = max(
            d["peak_bytes_in_use"] for d in per_device)
    return out


class MemorySampler:
    """Per-step raw-totals sampler (PR-2 API, unchanged): samples land in
    the metrics registry as ``memory/*`` gauges and as ``kind: "memory"``
    structured events.  The ledger consumes the SAME collection path
    (:func:`collect_raw_totals`) — there is no parallel poll."""

    def __init__(self, metrics, events=None, interval: int = 1):
        self.metrics = metrics
        self.events = events
        #: sample every N steps; 0 disables periodic sampling
        self.interval = int(interval)

    def maybe_sample(self, step: int) -> Optional[Dict[str, Any]]:
        if self.interval <= 0 or (step % self.interval) != 0:
            return None
        return self.sample(step=step)

    def sample(self, step: Optional[int] = None) -> Dict[str, Any]:
        out = collect_raw_totals()
        if self.metrics is not None:
            if "live_array_bytes" in out:
                self.metrics.gauge("memory/live_array_bytes").set(
                    out["live_array_bytes"])
                self.metrics.gauge("memory/live_array_count").set(
                    out["live_array_count"])
            if "device_bytes_in_use" in out:
                self.metrics.gauge("memory/device_bytes_in_use").set(
                    out["device_bytes_in_use"])
                self.metrics.gauge("memory/device_peak_bytes_in_use").set(
                    out["device_peak_bytes_in_use"])
        if self.events is not None and out:
            fields = dict(out)
            if step is not None:
                fields["step"] = int(step)
            self.events.emit("memory", **fields)
        if step is not None:
            out["step"] = int(step)
        return out


class MemoryLedger:
    """Bucketed attribution of live device bytes with a conservation
    invariant.  Sources are zero-arg callables returning current bytes for
    ONE bucket; they are polled at :meth:`snapshot` time (cheap: the
    engine's are O(1) attribute reads)."""

    def __init__(self, component: str = "proc",
                 eps: float = CONSERVATION_EPS):
        self.component = component
        self.eps = float(eps)
        self._lock = threading.Lock()
        self._sources: Dict[str, List[Callable[[], int]]] = \
            {b: [] for b in MEM_BUCKETS}
        self._kv_fn: Optional[Callable[[], Optional[Dict]]] = None
        self._swap_fn: Optional[Callable[[], Optional[Dict]]] = None
        self._baseline_other = 0
        self._was_conserved = True
        self.unattributed_incidents = 0

    # ------------------------------------------------------------------ #
    # Wiring
    # ------------------------------------------------------------------ #
    def register_source(self, bucket: str, fn: Callable[[], int]) -> None:
        """Register a byte source for ``bucket``.  Raises on an unknown
        bucket — a typo'd source must fail loudly, not open an eighth
        bucket the conservation tests don't know about."""
        if bucket not in self._sources:
            raise ValueError(f"unknown memory bucket {bucket!r} "
                             f"(must be one of {MEM_BUCKETS})")
        with self._lock:
            self._sources[bucket].append(fn)

    def attach_kv(self, fn: Callable[[], Optional[Dict]]) -> None:
        """Attach the engine's heat-snapshot provider (``kv`` section of
        every snapshot; None while tracking is off)."""
        self._kv_fn = fn

    def attach_swap(self, fn: Callable[[], Optional[Dict]]) -> None:
        """Attach the KV swap manager's stats provider (``swap`` section:
        hit rate, swap in/out bytes, avoided recompute tokens — the live
        numbers ``dstpu-mem --validate`` checks against the what-if
        prediction)."""
        self._swap_fn = fn

    def capture_baseline(self) -> int:
        """Fold bytes that pre-date this ledger's sources (JAX runtime
        constants, other components' arrays) into ``other`` once, so
        conservation judges only what changes afterwards."""
        raw = collect_raw_totals()
        live = int(raw.get("live_array_bytes", 0) or 0)
        self._baseline_other = max(0, live - self._attributed_bytes())
        return self._baseline_other

    def _attributed_bytes(self) -> int:
        total = 0
        with self._lock:
            sources = {b: list(fns) for b, fns in self._sources.items()}
        for b, fns in sources.items():
            if b in NON_DEVICE_BUCKETS:
                continue
            for fn in fns:
                try:
                    total += int(fn() or 0)
                except Exception:
                    continue
        return total

    # ------------------------------------------------------------------ #
    # Reading
    # ------------------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Any]:
        raw = collect_raw_totals()
        with self._lock:
            sources = {b: list(fns) for b, fns in self._sources.items()}
            baseline = self._baseline_other
        buckets: Dict[str, int] = {}
        for b, fns in sources.items():
            total = 0
            for fn in fns:
                try:
                    total += int(fn() or 0)
                except Exception:
                    continue
            buckets[b] = total
        buckets["other"] += baseline
        live = int(raw.get("live_array_bytes", 0) or 0)
        attributed = sum(v for b, v in buckets.items()
                         if b not in NON_DEVICE_BUCKETS)
        unattributed = live - attributed
        denom = max(live, 1)
        snap: Dict[str, Any] = {
            "component": self.component,
            "live_bytes": live,
            "live_array_count": int(raw.get("live_array_count", 0) or 0),
            "device_bytes_in_use": int(
                raw.get("device_bytes_in_use", 0) or 0),
            "device_peak_bytes_in_use": int(
                raw.get("device_peak_bytes_in_use", 0) or 0),
            "buckets": buckets,
            "fractions": {b: round(v / denom, 6)
                          for b, v in buckets.items()},
            "unattributed_bytes": unattributed,
            "unattributed_frac": round(unattributed / denom, 6),
            "conserved": abs(unattributed) <= self.eps * denom,
        }
        if self._kv_fn is not None:
            try:
                kv = self._kv_fn()
            except Exception:
                kv = None
            if kv:
                snap["kv"] = kv
        if self._swap_fn is not None:
            try:
                swap = self._swap_fn()
            except Exception:
                swap = None
            if swap:
                snap["swap"] = swap
        return snap

    # ------------------------------------------------------------------ #
    # Registry surface
    # ------------------------------------------------------------------ #
    def publish(self, heat_event: bool = False) -> Dict[str, Any]:
        """Mirror a fresh snapshot into ``mem/*`` gauges; emit the
        edge-triggered ``mem_unattributed`` incident on a conservation
        break; optionally emit a ``kv_heat`` trace event (the recorded
        input to the dstpu-mem what-if-spill estimator — callers pick the
        cadence, it carries per-page ages)."""
        from .hub import get_telemetry

        snap = self.snapshot()
        tel = get_telemetry()
        if tel is not None:
            m = tel.metrics
            m.gauge("mem/live_bytes").set(snap["live_bytes"])
            for b, v in snap["buckets"].items():
                m.gauge(f"mem/{b}_bytes").set(v)
            m.gauge("mem/unattributed_bytes").set(snap["unattributed_bytes"])
            m.gauge("mem/unattributed_frac").set(snap["unattributed_frac"])
            m.gauge("mem/conserved").set(1 if snap["conserved"] else 0)
            kv = snap.get("kv")
            if kv:
                m.gauge("mem/kv_live_pages").set(kv["live_pages"])
                m.gauge("mem/kv_peak_pages").set(kv["peak_live_pages"])
                m.gauge("mem/kv_used_bytes").set(kv["used_bytes"])
                m.gauge("mem/prefix_shared_bytes_saved").set(
                    kv["prefix_shared_bytes_saved"])
                for thr, n in kv.get("cold_pages", {}).items():
                    m.gauge("mem/kv_cold_pages").set(n, age_windows=str(thr))
                for t, d in kv.get("tenants", {}).items():
                    m.gauge("mem/tenant_kv_bytes").set(d["bytes"], tenant=t)
            swap = snap.get("swap")
            if swap:
                m.gauge("mem/swap_in_bytes").set(swap["swap_in_bytes"])
                m.gauge("mem/swap_out_bytes").set(swap["swap_out_bytes"])
                m.gauge("mem/swap_hit_rate").set(
                    round(float(swap["hit_rate"]), 6))
        if not snap["conserved"] and self._was_conserved:
            self.unattributed_incidents += 1
            if tel is not None:
                tel.metrics.counter("mem/unattributed").inc()
                tel.event("mem_unattributed",
                          component=self.component,
                          live_bytes=snap["live_bytes"],
                          unattributed_bytes=snap["unattributed_bytes"],
                          unattributed_frac=snap["unattributed_frac"],
                          buckets=snap["buckets"])
        self._was_conserved = snap["conserved"]
        if heat_event and tel is not None and snap.get("kv"):
            tel.event("kv_heat", component=self.component, **snap["kv"])
        return snap


def rollup(snapshots: Iterable[Optional[Dict[str, Any]]],
           component: str = "fleet") -> Dict[str, Any]:
    """Sum per-process ledger snapshots (scraped replica ``/memory`` or
    ``/healthz`` bodies) into one fleet-level view.  Tolerant of None /
    malformed entries — a half-scraped replica must degrade the rollup,
    never kill the endpoint."""
    live = 0
    unattr = 0
    n = 0
    bad = 0
    buckets: Dict[str, int] = {b: 0 for b in MEM_BUCKETS}
    kv_live = kv_peak = kv_used = kv_saved = 0
    kv_cold: Dict[str, int] = {}
    tenants: Dict[str, int] = {}
    kv_seen = False
    swap_seen = False
    swap_sum: Dict[str, float] = {"swapped_out": 0, "swapped_in": 0,
                                  "misses": 0, "swap_in_bytes": 0,
                                  "swap_out_bytes": 0,
                                  "avoided_recompute_tokens": 0,
                                  "host_used_bytes": 0,
                                  "host_capacity_bytes": 0}
    for s in snapshots:
        if not isinstance(s, dict) or "live_bytes" not in s:
            continue                  # not a ledger snapshot at all
        n += 1
        try:
            live += int(s.get("live_bytes") or 0)
            unattr += int(s.get("unattributed_bytes") or 0)
            if s.get("conserved") is False:
                bad += 1
            for b in MEM_BUCKETS:
                buckets[b] += int((s.get("buckets") or {}).get(b) or 0)
            kv = s.get("kv")
            if isinstance(kv, dict):
                kv_seen = True
                kv_live += int(kv.get("live_pages") or 0)
                kv_peak += int(kv.get("peak_live_pages") or 0)
                kv_used += int(kv.get("used_bytes") or 0)
                kv_saved += int(kv.get("prefix_shared_bytes_saved") or 0)
                for thr, c in (kv.get("cold_pages") or {}).items():
                    kv_cold[str(thr)] = kv_cold.get(str(thr), 0) + int(c)
                for t, d in (kv.get("tenants") or {}).items():
                    tenants[str(t)] = tenants.get(str(t), 0) + \
                        int((d or {}).get("bytes") or 0)
            swap = s.get("swap")
            if isinstance(swap, dict):
                swap_seen = True
                for k in swap_sum:
                    swap_sum[k] += int(swap.get(k) or 0)
        except (TypeError, ValueError, AttributeError):
            continue
    denom = max(live, 1)
    out: Dict[str, Any] = {
        "component": component,
        "processes": n,
        "live_bytes": live,
        "buckets": buckets,
        "fractions": {b: round(v / denom, 6) for b, v in buckets.items()},
        "unattributed_bytes": unattr,
        "unattributed_frac": round(unattr / denom, 6),
        "nonconserved_processes": bad,
        "conserved": bad == 0 and abs(unattr) <= CONSERVATION_EPS * denom,
    }
    if kv_seen:
        out["kv"] = {
            "live_pages": kv_live,
            "peak_live_pages": kv_peak,
            "used_bytes": kv_used,
            "prefix_shared_bytes_saved": kv_saved,
            "cold_pages": dict(sorted(kv_cold.items(),
                                      key=lambda kv_: int(kv_[0]))),
            "tenants": {t: {"bytes": v}
                        for t, v in sorted(tenants.items())},
        }
    if swap_seen:
        hits = swap_sum["swapped_in"]
        total = hits + swap_sum["misses"]
        out["swap"] = {**{k: int(v) for k, v in swap_sum.items()},
                       "hit_rate": hits / max(1, total) if total else 1.0}
    return out


# --------------------------------------------------------------------- #
# Process-global instance (goodput-ledger install pattern)
# --------------------------------------------------------------------- #
_GLOBAL: Optional[MemoryLedger] = None
_GLOBAL_LOCK = threading.Lock()


def install_memory_ledger(ledger: Optional[MemoryLedger]
                          ) -> Optional[MemoryLedger]:
    """Install (or clear, with None) the process-global memory ledger."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        previous, _GLOBAL = _GLOBAL, ledger
    return previous


def get_memory_ledger() -> Optional[MemoryLedger]:
    return _GLOBAL


#: package-level re-export names (``rollup`` is too generic un-prefixed)
rollup_memory = rollup
