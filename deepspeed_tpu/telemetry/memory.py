"""Per-step memory sampler.

What memory "did" during a run is the question HBM-bound training debugging
always starts with.  Two complementary sources, both polled from the host:

  * ``jax.live_arrays()`` — every live jax.Array this process holds a
    reference to, summed into total bytes + count (catches Python-side leaks:
    a list someone keeps appending device arrays to);
  * ``device.memory_stats()`` — the runtime allocator's view
    (``bytes_in_use`` / ``peak_bytes_in_use``) where the backend provides it
    (TPU does; CPU may return None/{}).

Samples land in the metrics registry (gauges track the high-water mark
automatically) and as ``kind: "memory"`` structured events, so the run
summary can print the peak and when it happened.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


class MemorySampler:
    def __init__(self, metrics, events=None, interval: int = 1):
        self.metrics = metrics
        self.events = events
        #: sample every N steps; 0 disables periodic sampling
        self.interval = int(interval)

    def maybe_sample(self, step: int) -> Optional[Dict[str, Any]]:
        if self.interval <= 0 or (step % self.interval) != 0:
            return None
        return self.sample(step=step)

    def sample(self, step: Optional[int] = None) -> Dict[str, Any]:
        import jax

        out: Dict[str, Any] = {}
        try:
            live = jax.live_arrays()
            out["live_array_bytes"] = int(
                sum(getattr(a, "nbytes", 0) or 0 for a in live))
            out["live_array_count"] = len(live)
        except Exception:
            pass

        per_device = []
        try:
            for d in jax.local_devices():
                stats = None
                try:
                    stats = d.memory_stats()
                except Exception:
                    stats = None
                if not stats:
                    continue
                per_device.append({
                    "device": str(d.id),
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                })
        except Exception:
            pass
        if per_device:
            out["device_bytes_in_use"] = sum(
                d["bytes_in_use"] for d in per_device)
            out["device_peak_bytes_in_use"] = max(
                d["peak_bytes_in_use"] for d in per_device)

        if self.metrics is not None:
            if "live_array_bytes" in out:
                self.metrics.gauge("memory/live_array_bytes").set(
                    out["live_array_bytes"])
                self.metrics.gauge("memory/live_array_count").set(
                    out["live_array_count"])
            if "device_bytes_in_use" in out:
                self.metrics.gauge("memory/device_bytes_in_use").set(
                    out["device_bytes_in_use"])
                self.metrics.gauge("memory/device_peak_bytes_in_use").set(
                    out["device_peak_bytes_in_use"])
        if self.events is not None and out:
            fields = dict(out)
            if step is not None:
                fields["step"] = int(step)
            self.events.emit("memory", **fields)
        if step is not None:
            out["step"] = int(step)
        return out
