"""Span-based structured tracer.

``tracer.span("fwd")`` nests (thread-local stack), records wall-clock
durations, optionally fences on a JAX value (``block_until_ready``) so the
measured time covers device execution instead of dispatch, and mirrors every
span into ``jax.profiler.TraceAnnotation`` so spans line up with XLA ops when
an xprof/jax profile is active.  ``step_span`` is the
``StepTraceAnnotation`` analogue that delimits whole training steps.

Export: :meth:`Tracer.to_chrome_trace` renders the recorded spans as a
Chrome-trace/Perfetto-compatible JSON object (``ph: "X"`` complete events,
microsecond timestamps) so a run can be dropped into ``chrome://tracing`` or
https://ui.perfetto.dev with no conversion step.

Disabled cost: a disabled tracer hands back one shared no-op span object —
no allocation, no locking — so instrumentation can stay in the hot path
unconditionally.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _NullSpan:
    """Shared do-nothing span for disabled telemetry (zero per-call cost)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self

    def fence_on(self, value):
        return self


NULL_SPAN = _NullSpan()


class SpanRecord:
    __slots__ = ("name", "start_s", "dur_s", "depth", "parent", "tid",
                 "attrs", "error")

    def __init__(self, name: str, start_s: float, dur_s: float, depth: int,
                 parent: Optional[str], tid: int,
                 attrs: Optional[Dict[str, Any]], error: Optional[str]):
        self.name = name
        self.start_s = start_s      # seconds since tracer epoch
        self.dur_s = dur_s
        self.depth = depth
        self.parent = parent
        self.tid = tid
        self.attrs = attrs
        self.error = error

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "start_s": round(self.start_s, 9),
             "dur_s": round(self.dur_s, 9), "depth": self.depth,
             "parent": self.parent, "tid": self.tid}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.error:
            d["error"] = self.error
        return d


class _Span:
    __slots__ = ("_tracer", "name", "_attrs", "_sync", "_t0", "_annotation",
                 "_step_num")

    def __init__(self, tracer: "Tracer", name: str, sync: Any,
                 attrs: Optional[Dict[str, Any]], step_num: Optional[int] = None):
        self._tracer = tracer
        self.name = name
        self._attrs = attrs
        self._sync = sync
        self._t0 = 0.0
        self._annotation = None
        self._step_num = step_num

    def set(self, **attrs) -> "_Span":
        """Attach attributes after entry (e.g. values known only mid-span)."""
        if self._attrs is None:
            self._attrs = {}
        self._attrs.update(attrs)
        return self

    def fence_on(self, value) -> "_Span":
        """Fence span exit on ``value`` (``jax.block_until_ready``) — for
        sync targets that only exist mid-span, e.g. the step's loss."""
        self._sync = value
        return self

    def __enter__(self) -> "_Span":
        tracer = self._tracer
        stack = tracer._stack()
        stack.append(self)
        if tracer.jax_annotations:
            try:
                import jax

                if self._step_num is not None:
                    self._annotation = jax.profiler.StepTraceAnnotation(
                        self.name, step_num=self._step_num)
                else:
                    self._annotation = jax.profiler.TraceAnnotation(self.name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        tracer = self._tracer
        end = time.perf_counter()
        try:
            if self._sync is not None and exc_type is None:
                try:
                    import jax

                    jax.block_until_ready(self._sync)
                    end = time.perf_counter()
                except Exception:
                    pass
            if self._annotation is not None:
                try:
                    self._annotation.__exit__(exc_type, exc, tb)
                except Exception:
                    pass
        finally:
            stack = tracer._stack()
            depth = len(stack) - 1
            if stack and stack[-1] is self:
                stack.pop()
            else:  # unbalanced exit — drop up to and including this span
                while stack:
                    if stack.pop() is self:
                        break
            parent = stack[-1].name if stack else None
            tracer._record(SpanRecord(
                name=self.name,
                start_s=self._t0 - tracer._epoch,
                dur_s=end - self._t0,
                depth=max(depth, 0),
                parent=parent,
                tid=threading.get_ident(),
                attrs=self._attrs,
                error=exc_type.__name__ if exc_type is not None else None))
        return False  # never swallow the exception


class Tracer:
    """Records nested spans; exports Chrome-trace JSON.

    Parameters
    ----------
    enabled: disabled tracers return the shared :data:`NULL_SPAN`.
    max_spans: ring-buffer cap — the newest spans win, and a dropped-span
        counter records how many fell off (no silent truncation).
    jax_annotations: mirror spans into ``jax.profiler.TraceAnnotation``.
    """

    def __init__(self, enabled: bool = True, max_spans: int = 100_000,
                 jax_annotations: bool = True):
        self.enabled = enabled
        self.max_spans = max(int(max_spans), 1)
        self.jax_annotations = jax_annotations
        self.dropped = 0
        self.total_recorded = 0   # monotonic; never decreases on eviction
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        self._lock = threading.Lock()
        self._spans: "collections.deque[SpanRecord]" = collections.deque(
            maxlen=self.max_spans)
        self._tls = threading.local()

    # ---------------------------------------------------------------- #
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _record(self, rec: SpanRecord) -> None:
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.dropped += 1   # deque(maxlen) evicts the oldest in O(1)
            self._spans.append(rec)
            self.total_recorded += 1

    # ---------------------------------------------------------------- #
    def span(self, name: str, sync: Any = None, **attrs):
        """Context manager for one timed span.

        ``sync``: a JAX value to ``block_until_ready`` at span exit, so the
        span covers device time, not just Python dispatch.
        """
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, sync, attrs or None)

    def step_span(self, step_num: int, name: str = "train_step",
                  sync: Any = None):
        """Step-delimiting span; also emits ``StepTraceAnnotation`` so an
        active JAX profile groups device ops per training step."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, sync, {"step": int(step_num)},
                     step_num=int(step_num))

    def current_span(self) -> Optional[str]:
        stack = self._stack()
        return stack[-1].name if stack else None

    def depth(self) -> int:
        return len(self._stack())

    # ---------------------------------------------------------------- #
    def records(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def snapshot(self) -> Tuple[List[SpanRecord], int]:
        """(buffered records, total ever recorded) read atomically — the
        incremental-export bookkeeping in ``Telemetry.flush`` needs both from
        the same instant or ring eviction between the two reads skews it."""
        with self._lock:
            return list(self._spans), self.total_recorded

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0
            self.total_recorded = 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace JSON object (``chrome://tracing`` / Perfetto)."""
        events = []
        for rec in self.records():
            ev = {
                "name": rec.name,
                "ph": "X",
                "ts": rec.start_s * 1e6,     # µs
                "dur": rec.dur_s * 1e6,
                "pid": 0,
                "tid": rec.tid,
                "args": dict(rec.attrs or {}),
            }
            if rec.error:
                ev["args"]["error"] = rec.error
            if rec.parent:
                ev["args"]["parent"] = rec.parent
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"epoch_unix_s": self._epoch_unix,
                         "dropped_spans": self.dropped},
        }

    def export_chrome_trace(self, path: str) -> str:
        import json
        import os

        from ..runtime.fault.atomic import atomic_write_text

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_text(path, json.dumps(self.to_chrome_trace()))
        return path
