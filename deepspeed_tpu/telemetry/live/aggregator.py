"""Cross-host snapshot aggregation for the live observability plane.

A pod-scale run has one live server (host 0) but N hosts' worth of health:
each non-zero host periodically pushes a *compact* snapshot — label-free
gauge values, incident-counter totals, its last completed step — over plain
HTTP to host 0's ``/push`` endpoint.  The push rides the fault subsystem's
``@retryable`` backoff (a flaky NIC or a server mid-restart is exactly the
transient the policy exists for) and never touches the collective path: a
host that can't push trains on; its series just go stale, which the
aggregator surfaces as ``live/push_age_s``.

Host 0 folds the snapshots into ``/metrics`` as ``host``-labelled series
(``cluster_<name>{host="N"}``, kept apart from host 0's own unlabelled
series so the two can never merge into one stream) plus the cross-host step
skew — the live analogue of the offline straggler detector's signal.
"""
from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from ...runtime.fault.retry import RetryPolicy, retryable
from ...utils.logging import logger
from ..events import _jsonable
from ..metrics import _prom_name

#: pushed restart reasons land in a Prometheus label on host 0 — strip
#: anything that could break exposition quoting, cap the length (the
#: legitimate vocabulary is "exit:N" / "signal:N")
_REASON_SAFE = re.compile(r"[^A-Za-z0-9_:. \-]")

#: counters whose totals ride every snapshot (the incident digest)
INCIDENT_COUNTERS = ("fault/events", "anomaly/events", "straggler/events",
                     "serving/nan_isolated", "serving/window_hang",
                     "mem/unattributed")


def collect_snapshot(telemetry, host_id: int,
                     step: Optional[int] = None) -> Dict[str, Any]:
    """One host's compact push payload: label-free gauges (labelled series
    are usually high-cardinality per-op detail — the pod view wants health,
    not a full mirror), incident totals, and the last completed step."""
    # gauge_values, not the full snapshot(): this runs every push interval
    # beside the training thread, and snapshot() sorts every histogram
    # reservoir under the registry lock only for the rows to be discarded
    gauges: Dict[str, float] = telemetry.metrics.gauge_values()
    counters: Dict[str, float] = {}
    for name in INCIDENT_COUNTERS:
        m = telemetry.metrics.get(name)
        if m is not None and hasattr(m, "total"):
            counters[name] = m.total()
    snap: Dict[str, Any] = {"host": int(host_id), "ts": time.time(),
                            "step": step, "gauges": gauges,
                            "counters": counters}
    # the restart REASON lives in a labelled gauge (which the label-free
    # filter above drops) — ride it as a dedicated field so host 0 can
    # still show WHY this host's last incarnation died
    from .server import elastic_state_from_env

    state = elastic_state_from_env()
    if state["last_failure"] is not None:
        snap["elastic"] = state
    return snap


class CrossHostAggregator:
    """Latest-snapshot-per-host store behind the host-0 server.

    ``local_host`` is the serving host's own id: a push claiming it is
    rejected, or an unauthenticated POST could override host 0's locally
    observed step/series and fabricate (or mask) a straggler signal.

    Retention is bounded: snapshots are kept per host id forever (that is
    the point — a host that stops pushing must stay visible as stale), so
    without ``max_hosts``/``max_series_per_push`` caps a pusher cycling
    through fabricated host ids or gauge names could grow host 0's RSS and
    /metrics cardinality without limit.  Over-cap pushes are rejected (a
    400, like any other malformed snapshot); known hosts always update in
    place."""

    def __init__(self, local_host: Optional[int] = None,
                 max_hosts: int = 1024, max_series_per_push: int = 512):
        self.local_host = local_host
        self.max_hosts = int(max_hosts)
        self.max_series_per_push = int(max_series_per_push)
        self._lock = threading.Lock()
        self._hosts: Dict[int, Dict[str, Any]] = {}

    def ingest(self, snapshot: Dict[str, Any]) -> None:
        """Validate-and-store.  The /push endpoint is an unauthenticated
        HTTP surface: one malformed value accepted here would make every
        subsequent /metrics render raise, so non-numeric gauges/counters
        are dropped and a bad step/host is a rejection, not a 500 factory."""
        if not isinstance(snapshot, dict):
            raise ValueError(f"snapshot must be a JSON object, "
                             f"got {type(snapshot).__name__}")
        host = int(snapshot.get("host", -1))
        if host < 0:
            raise ValueError(f"snapshot missing a valid host id: "
                             f"{snapshot.get('host')!r}")
        if self.local_host is not None and host == self.local_host:
            raise ValueError(f"snapshot claims the serving host's own id "
                             f"{host}; pushes must carry the sender's")
        step = snapshot.get("step")
        clean: Dict[str, Any] = {
            "host": host,
            "step": int(step) if isinstance(step, (int, float)) else None,
            "ts": float(snapshot["ts"])
            if isinstance(snapshot.get("ts"), (int, float)) else time.time(),
            "received_ts": time.time(),
        }
        for section in ("gauges", "counters"):
            raw = snapshot.get(section)
            clean[section] = {
                str(k): float(v) for k, v in raw.items()
                if isinstance(v, (int, float))
            } if isinstance(raw, dict) else {}
            if len(clean[section]) > self.max_series_per_push:
                raise ValueError(
                    f"snapshot {section} carries {len(clean[section])} "
                    f"series (cap {self.max_series_per_push}); a compact "
                    f"health push should be far smaller")
        el = snapshot.get("elastic")
        if isinstance(el, dict) and isinstance(el.get("last_failure"), str):
            clean["elastic"] = {
                "restart_count": int(el["restart_count"])
                if isinstance(el.get("restart_count"), (int, float)) else 0,
                "last_failure":
                    _REASON_SAFE.sub("_", el["last_failure"])[:64],
            }
        with self._lock:
            if host not in self._hosts and \
                    len(self._hosts) >= self.max_hosts:
                raise ValueError(
                    f"aggregator already tracks {self.max_hosts} hosts; "
                    f"rejecting new host id {host}")
            self._hosts[host] = clean

    def hosts(self) -> List[int]:
        with self._lock:
            return sorted(self._hosts)

    def snapshots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._hosts[h] for h in sorted(self._hosts)]

    # ---------------------------------------------------------------- #
    def step_skew(self, local_step: Optional[int] = None,
                  local_host: int = 0) -> Dict[str, Any]:
        """Per-host last-step table and the max-min spread: on a healthy
        pod every host pushes roughly the same step; a widening spread means
        one host is stalled/restarting while its peers wait in collectives."""
        steps: Dict[int, int] = {}
        if local_step is not None:
            steps[int(local_host)] = int(local_step)
        for snap in self.snapshots():
            if snap.get("step") is not None:
                steps[int(snap["host"])] = int(snap["step"])
        out: Dict[str, Any] = {"per_host": {str(h): s
                                            for h, s in sorted(steps.items())}}
        if steps:
            out["skew"] = max(steps.values()) - min(steps.values())
        return out

    def prometheus_lines(self, local_step: Optional[int] = None,
                         local_host: int = 0) -> List[str]:
        """``host``-labelled exposition lines appended to host 0's own
        ``/metrics`` rendering."""
        now = time.time()
        lines: List[str] = []
        if local_step is not None:
            # host 0's own step rides the same series as its peers' — a
            # per-host dashboard/alert must be able to see the serving
            # host stall too
            lines.append(f'live_host_step{{host="{int(local_host)}"}} '
                         f'{int(local_step)}')
        for snap in self.snapshots():
            h = snap["host"]
            for name, value in sorted(snap.get("gauges", {}).items()):
                lines.append(
                    f'cluster_{_prom_name(name)}{{host="{h}"}} {value:g}')
            for name, value in sorted(snap.get("counters", {}).items()):
                lines.append(
                    f'cluster_{_prom_name(name)}{{host="{h}"}} {value:g}')
            if snap.get("step") is not None:
                lines.append(f'live_host_step{{host="{h}"}} '
                             f'{int(snap["step"])}')
            el = snap.get("elastic")
            if el and el.get("last_failure"):
                lines.append(
                    f'cluster_elastic_last_restart{{host="{h}",'
                    f'reason="{el["last_failure"]}"}} 1')
            age = now - float(snap.get("received_ts", now))
            lines.append(f'live_push_age_s{{host="{h}"}} {age:g}')
        skew = self.step_skew(local_step=local_step, local_host=local_host)
        if "skew" in skew:
            lines.append(f'live_step_skew {skew["skew"]}')
        return lines


# ------------------------------------------------------------------- #
# Push side (non-zero hosts)
# ------------------------------------------------------------------- #
def push_snapshot(url: str, snapshot: Dict[str, Any],
                  timeout_s: float = 5.0) -> None:
    """POST one snapshot to host 0's ``/push`` (single attempt —
    :class:`SnapshotPusher` wraps this in ``@retryable``).
    ``urllib.error.URLError`` subclasses ``OSError``, so the fault
    subsystem's default retry-on set covers it; a 4xx rejection is
    re-raised as ValueError so a deterministic misconfiguration (e.g. a
    host-id clash) fails fast instead of burning the whole backoff budget
    every push interval."""
    # _jsonable (the event log's encoder) turns numpy scalars into real
    # JSON numbers; default=str would stringify them and ingest's numeric
    # filter on host 0 would then silently drop the series
    body = json.dumps(snapshot, default=_jsonable).encode()
    req = urllib.request.Request(
        url.rstrip("/") + "/push", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            resp.read()
    except urllib.error.HTTPError as e:
        if 400 <= e.code < 500:
            raise ValueError(
                f"push rejected by {url}: HTTP {e.code} {e.reason}") from e
        raise          # 5xx: the server may recover — stays retryable


class SnapshotPusher:
    """Daemon thread on every non-zero host: every ``interval_s`` collect a
    compact snapshot and push it.  Exhausted retries are counted
    (``live/push_failures``) and skipped — the next interval tries again;
    observability must never take the training loop down with it."""

    def __init__(self, telemetry, url: str, host_id: int,
                 step_fn: Optional[Callable[[], Optional[int]]] = None,
                 interval_s: float = 10.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 timeout_s: float = 5.0):
        self.telemetry = telemetry
        self.url = url
        self.host_id = int(host_id)
        self.step_fn = step_fn
        self.interval_s = float(interval_s)
        #: consulted by @retryable via the policy_attr seam (_push is a
        #: bound method, args[0] is this instance) — config.fault shapes
        #: the backoff exactly as it does for checkpoint I/O
        self.retry_policy = retry_policy or RetryPolicy.from_env()
        self.timeout_s = float(timeout_s)
        self.pushed = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def push_now(self, retry: bool = True) -> bool:
        """One collect+push cycle; True on success.  Public so tests (and a
        final flush on close) can push without waiting out the interval.
        ``retry=False`` makes it a single attempt — the final push in
        ``engine.close()`` must not serially burn the whole backoff budget
        (tens of seconds) when host 0 is the reason the job is shutting
        down."""
        step = None
        if self.step_fn is not None:
            try:
                step = self.step_fn()
            except Exception:  # noqa: BLE001 — a step probe must not stop pushes
                step = None
        snapshot = collect_snapshot(self.telemetry, self.host_id, step=step)
        try:
            if retry:
                self._push(snapshot)
            else:
                push_snapshot(self.url, snapshot, timeout_s=self.timeout_s)
        except Exception as e:  # noqa: BLE001 — retries exhausted; see docstring
            self.failures += 1
            self.telemetry.metrics.counter("live/push_failures").inc()
            logger.warning(
                f"live snapshot push to {self.url} failed"
                f"{' (attempt budget exhausted)' if retry else ''}: {e!r}")
            return False
        self.pushed += 1
        return True

    @retryable(op_name="live_push")
    def _push(self, snapshot: Dict[str, Any]) -> None:
        push_snapshot(self.url, snapshot, timeout_s=self.timeout_s)

    def _run(self) -> None:
        # push-then-wait: a freshly (re)started host must land on host 0's
        # /metrics immediately, not one full interval later — right after
        # an elastic restart is exactly when an operator is watching
        while True:
            self.push_now()
            if self._stop.wait(self.interval_s):
                return

    def start(self) -> "SnapshotPusher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dstpu-live-pusher", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
