"""Live observability HTTP plane: stdlib server over the telemetry hub.

One ``ThreadingHTTPServer`` (no dependencies beyond the stdlib) runs on
host 0 beside the training loop and serves the run *while it is running*:

  * ``GET /metrics``  — Prometheus text exposition: host 0's registry plus
    the ``host``-labelled series aggregated from non-zero hosts' pushes and
    the cross-host step skew;
  * ``GET /healthz``  — machine-checkable liveness: watchdog heartbeat age,
    last completed step, incident counts, elastic restart state.  Status is
    ``healthy`` / ``recovering`` / ``degraded`` / ``hung``; anything but
    ``healthy`` answers HTTP 503 so a dumb prober (k8s, a load balancer, a
    cron curl) needs zero JSON parsing;
  * ``GET /events``   — Server-Sent-Events tail of the structured event
    stream (replay of the newest ring entries, then live follow);
  * ``GET /summary``  — the ``dstpu-telemetry`` digest computed from live
    in-memory state (spans, metrics, events), no flush required;
  * ``POST /push``    — ingest endpoint for non-zero hosts' snapshots
    (see ``aggregator.py``).

Everything here is read-mostly and already thread-safe underneath (registry
lock, tracer lock, event-log lock + cursor), so request handlers never
block the training thread beyond those short critical sections.
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from ...utils.logging import logger
from ..events import _jsonable
from .aggregator import INCIDENT_COUNTERS, CrossHostAggregator

#: /healthz statuses, in decreasing severity
STATUS_HUNG = "hung"
STATUS_RECOVERING = "recovering"
STATUS_DEGRADED = "degraded"
STATUS_HEALTHY = "healthy"


def elastic_state_from_env() -> Dict[str, Any]:
    """Elastic-agent restart breadcrumbs the agent leaves in the worker
    env: how many times this gang has been restarted and why the last
    incarnation died.  Absent env (no agent) reads as a fresh gang."""
    try:
        restarts = int(os.environ.get("DSTPU_ELASTIC_RESTART_COUNT", 0))
    except ValueError:
        restarts = 0
    last_rc = os.environ.get("DSTPU_ELASTIC_LAST_RC")
    reason = None
    if last_rc is not None:
        try:
            rc = int(last_rc)
            reason = f"signal:{-rc}" if rc < 0 else f"exit:{rc}"
        except ValueError:
            reason = str(last_rc)
    try:
        reshapes = int(os.environ.get("DSTPU_ELASTIC_RESHAPE_COUNT", 0))
    except ValueError:
        reshapes = 0
    # set ONLY while the agent runs the gang on a different shape than it
    # was launched with (--allow-reshape); cleared when capacity returns
    mesh_shape = os.environ.get("DSTPU_ELASTIC_MESH_SHAPE") or None
    return {"restart_count": restarts, "last_failure": reason,
            "reshape_count": reshapes, "mesh_shape": mesh_shape,
            "reshaped": mesh_shape is not None}


def publish_elastic_gauges(metrics) -> Dict[str, Any]:
    """Mirror the elastic restart state into the registry so ``/metrics``
    (and pushed snapshots) carry it: a scrape can distinguish 'recovering
    after restart 2' from 'healthy since boot' without hitting /healthz."""
    state = elastic_state_from_env()
    metrics.gauge("elastic/restart_count").set(state["restart_count"])
    metrics.gauge("elastic/reshape_count").set(state["reshape_count"])
    if state["reshaped"]:
        g = metrics.gauge("elastic/degraded")
        for key in g.labelsets():
            g.set(0, **dict(key))
        g.set(1, reason="reshaped")
    if state["last_failure"] is not None:
        # exactly one reason series carries 1 — zero any stale labelset
        # first (a gang that died as exit:1 then signal:9 must not expose
        # both as "last")
        g = metrics.gauge("elastic/last_restart")
        for key in g.labelsets():
            g.set(0, **dict(key))
        g.set(1, reason=state["last_failure"])
    return state


def health_report(telemetry, watchdog=None, anomaly=None,
                  step_fn: Optional[Callable[[], Optional[int]]] = None,
                  steps_this_process_fn: Optional[Callable[[], int]] = None,
                  aggregator: Optional[CrossHostAggregator] = None,
                  recovered_after_steps: int = 3,
                  degraded_window_steps: int = 16) -> Dict[str, Any]:
    """The /healthz body.  Also usable headless (tests, a debugger)."""
    wd = watchdog.dump() if watchdog is not None else None
    elastic = elastic_state_from_env()
    last_step = None
    if step_fn is not None:
        try:
            last_step = step_fn()
        except Exception:  # noqa: BLE001 — health must render regardless
            last_step = None
    if last_step is None and wd is not None:
        last_step = wd.get("step")

    incidents: Dict[str, float] = {}
    m = telemetry.metrics
    for name in INCIDENT_COUNTERS:
        metric = m.get(name)
        if metric is not None and hasattr(metric, "total"):
            incidents[name] = metric.total()
    if wd is not None:
        incidents["watchdog_timeouts"] = wd.get("timeouts", 0)

    reasons = []
    status = STATUS_HEALTHY
    # Mirror the watchdog's own semantics: a parked run (phase 'idle'
    # between steps / 'init' before the first) is quiet, not hung — only an
    # *active* phase past the deadline means a stuck collective/step.
    quiet = tuple(getattr(watchdog, "quiet_phases", ("init", "idle")))
    if wd is not None and wd.get("phase") not in quiet and \
            wd.get("last_heartbeat_age_s", 0) > wd.get(
                "deadline_s", float("inf")):
        status = STATUS_HUNG
        reasons.append(
            f"no heartbeat for {wd['last_heartbeat_age_s']}s "
            f"(deadline {wd['deadline_s']}s), phase={wd.get('phase')!r}")
    elif elastic["restart_count"] > 0 and steps_this_process_fn is not None \
            and steps_this_process_fn() < recovered_after_steps:
        status = STATUS_RECOVERING
        reasons.append(
            f"restart {elastic['restart_count']} "
            f"(last failure {elastic['last_failure']}), "
            f"{steps_this_process_fn()} step(s) into the new incarnation")
    elif elastic["reshaped"]:
        # the gang runs on a reshaped (usually shrunken) mesh: it makes
        # progress, but at changed capacity — degraded for the whole
        # incarnation, until the agent restores the launch-time shape
        status = STATUS_DEGRADED
        reasons.append(
            f'reshaped: gang re-planned to mesh {elastic["mesh_shape"]!r} '
            f'(reshape {elastic["reshape_count"]}, '
            f'restart {elastic["restart_count"]})')
    elif anomaly is not None and anomaly.last_incident_step is not None \
            and last_step is not None \
            and last_step - anomaly.last_incident_step <= degraded_window_steps:
        status = STATUS_DEGRADED
        reasons.append(
            f"anomaly {anomaly.last_incident_type!r} at step "
            f"{anomaly.last_incident_step} (now {last_step})")

    out: Dict[str, Any] = {
        "status": status,
        "reasons": reasons,
        "last_step": last_step,
        "incidents": incidents,
        "elastic": elastic,
        "ts": time.time(),
    }
    if wd is not None:
        out["watchdog"] = wd
    if aggregator is not None:
        out["step_skew"] = aggregator.step_skew(local_step=last_step)
    return out


def live_summary(telemetry, xprof: bool = False) -> Dict[str, Any]:
    """The ``dstpu-telemetry`` digest from *live* in-memory state: tracer
    ring spans, current registry snapshot, event ring.  Exactly the offline
    sections, minus the xprof parse (off by default — reading a trace dir
    mid-run is slow and the breadcrumb may not exist yet)."""
    from ..summary import (comm_table, incident_summary, memory_summary,
                           overlap_summary, profile_summary, step_breakdown)

    records, total_spans = telemetry.tracer.snapshot()
    spans = [r.to_dict() for r in records]
    metrics = telemetry.metrics.snapshot()
    events = telemetry.events.recent()
    profile = profile_summary(events, metrics)
    device_kind = (profile.get("roofline_gauges") or {}).get("device_kind")
    out = {
        "live": True,
        "n_spans": total_spans,
        "step_breakdown": step_breakdown(spans),
        "comm": comm_table(metrics, device_kind=device_kind),
        "overlap": overlap_summary(metrics),
        "profile": profile,
        "memory": memory_summary(metrics, events),
        "incidents": incident_summary(events),
    }
    if xprof:
        from ..summary import xprof_summary

        out["xprof"] = xprof_summary(events)
    return out


# ------------------------------------------------------------------- #
class _LiveHandler(BaseHTTPRequestHandler):
    """One request handler; all state lives on ``self.server`` (the
    ThreadingHTTPServer subclass below)."""

    server_version = "dstpu-live/1"
    protocol_version = "HTTP/1.1"
    #: set once an SSE response's headers are on the wire — after that a
    #: 500 would inject a second HTTP response mid-stream
    _streaming = False

    # BaseHTTPRequestHandler prints to stderr by default — route to the
    # rank-aware logger at debug level (a scrape per second is noise).
    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        logger.debug("live-server: " + format % args)

    # ---------------------------------------------------------------- #
    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj: Any) -> None:
        self._send(code, json.dumps(obj, default=_jsonable,
                                    sort_keys=True).encode() + b"\n",
                   "application/json")

    # ---------------------------------------------------------------- #
    def do_GET(self):  # noqa: N802 — stdlib hook name
        url = urlparse(self.path)
        try:
            if url.path == "/metrics":
                self._get_metrics()
            elif url.path == "/healthz":
                self._get_healthz()
            elif url.path == "/events":
                self._get_events(parse_qs(url.query))
            elif url.path == "/summary":
                self._get_summary(parse_qs(url.query))
            elif url.path == "/traces":
                from ..tracing import traces_endpoint_payload

                code, body = traces_endpoint_payload(parse_qs(url.query))
                self._send_json(code, body)
            elif url.path == "/":
                self._send_json(200, {"endpoints": [
                    "/metrics", "/healthz", "/events", "/summary",
                    "/traces"]})
            else:
                self._send_json(404, {"error": f"unknown path {url.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; nothing to answer
        except Exception as e:  # noqa: BLE001 — a handler bug must not 500 silently
            logger.warning(f"live-server {url.path} failed: {e!r}")
            if self._streaming:
                # the SSE response is already mid-flight; just drop the
                # connection instead of corrupting the stream
                self.close_connection = True
                return
            try:
                self._send_json(500, {"error": repr(e)})
            except (OSError, ValueError):
                pass

    def do_POST(self):  # noqa: N802 — stdlib hook name
        url = urlparse(self.path)
        try:
            if url.path == "/push":
                self._post_push()
            else:
                self._send_json(404, {"error": f"unknown path {url.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.warning(f"live-server {url.path} failed: {e!r}")
            try:
                self._send_json(500, {"error": repr(e)})
            except (OSError, ValueError):
                pass

    # ---------------------------------------------------------------- #
    def _get_metrics(self) -> None:
        srv = self.server
        text = srv.telemetry.metrics.prometheus_text()
        extra = srv.aggregator.prometheus_lines(
            local_step=srv.last_step(), local_host=srv.host_id)
        if extra:
            text += "\n".join(extra) + "\n"
        self._send(200, text.encode(), "text/plain; version=0.0.4")

    def _get_healthz(self) -> None:
        srv = self.server
        report = health_report(
            srv.telemetry, watchdog=srv.watchdog, anomaly=srv.anomaly,
            step_fn=srv.last_step,
            steps_this_process_fn=srv.steps_this_process,
            aggregator=srv.aggregator,
            recovered_after_steps=srv.recovered_after_steps,
            degraded_window_steps=srv.degraded_window_steps)
        code = 200 if report["status"] == STATUS_HEALTHY else 503
        self._send_json(code, report)

    def _get_summary(self, query: Dict[str, Any]) -> None:
        xprof = query.get("xprof", ["0"])[0] not in ("0", "false", "")
        self._send_json(200, live_summary(self.server.telemetry,
                                          xprof=xprof))

    def _post_push(self) -> None:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > 4 * 1024 * 1024:
            self._send_json(400, {"error": "missing/oversized body"})
            return
        try:
            snapshot = json.loads(self.rfile.read(length))
            self.server.aggregator.ingest(snapshot)
        except (ValueError, TypeError, AttributeError) as e:
            self._send_json(400, {"error": repr(e)})
            return
        self._send_json(200, {"ok": True,
                              "hosts": self.server.aggregator.hosts()})

    # ---------------------------------------------------------------- #
    def _get_events(self, query: Dict[str, Any]) -> None:
        """SSE tail: replay the newest ``replay`` ring events, then follow
        the cursor until the client disconnects, ``max`` new events arrive,
        or the server stops.  ``follow=0`` closes right after the replay
        (curl-able without hanging a terminal)."""
        srv = self.server
        log = srv.telemetry.events

        def _qint(name: str, default: int) -> int:
            try:
                return int(query.get(name, [default])[0])
            except (ValueError, TypeError):
                return default

        replay = max(_qint("replay", 25), 0)
        follow = query.get("follow", ["1"])[0] not in ("0", "false", "")
        max_new = _qint("max", 0)          # 0 = unbounded

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # an SSE stream has no length; hand-managed connection close
        self.send_header("Connection", "close")
        self.end_headers()
        self._streaming = True

        replayed, cursor = log.tail(replay)   # atomic: no dup into follow
        for rec in replayed:
            self._write_sse(rec)
        sent_new = 0
        while follow and not srv.stopping.is_set():
            fresh, cursor = log.events_since(cursor)
            for rec in fresh:
                self._write_sse(rec)
                sent_new += 1
                if max_new and sent_new >= max_new:
                    return
            if fresh:
                self.wfile.flush()
            if srv.stopping.wait(srv.sse_poll_s):
                return

    def _write_sse(self, rec: Dict[str, Any]) -> None:
        payload = json.dumps(rec, default=_jsonable)
        self.wfile.write(f"event: {rec.get('kind', 'event')}\n"
                         f"data: {payload}\n\n".encode())


class _LiveHTTPServer(ThreadingHTTPServer):
    daemon_threads = True          # SSE followers must not block exit
    allow_reuse_address = True

    # typed refs filled by LiveObservabilityServer.start()
    telemetry = None
    aggregator: CrossHostAggregator = None
    watchdog = None
    anomaly = None
    host_id = 0
    last_step: Callable[[], Optional[int]] = staticmethod(lambda: None)
    steps_this_process: Callable[[], int] = staticmethod(lambda: 0)
    recovered_after_steps = 3
    degraded_window_steps = 16
    sse_poll_s = 0.25
    stopping: threading.Event = None


class LiveObservabilityServer:
    """Owner object: builds the HTTP server on a daemon thread, exposes the
    bound port (``port=0`` picks a free one), and tears down cleanly.

    ``step_fn``/``steps_this_process_fn`` are host-side callables so the
    server never touches device state; the engine passes closures over its
    python-side counters."""

    def __init__(self, telemetry, port: int = 8790, bind: str = "0.0.0.0",
                 watchdog=None, anomaly=None, host_id: int = 0,
                 step_fn: Optional[Callable[[], Optional[int]]] = None,
                 steps_this_process_fn: Optional[Callable[[], int]] = None,
                 recovered_after_steps: int = 3,
                 degraded_window_steps: int = 16, sse_poll_s: float = 0.25):
        self.telemetry = telemetry
        self.requested_port = int(port)
        self.bind = bind
        self.watchdog = watchdog
        self.anomaly = anomaly
        self.host_id = int(host_id)
        self.step_fn = step_fn or (lambda: None)
        self.steps_this_process_fn = steps_this_process_fn or (lambda: 0)
        self.recovered_after_steps = int(recovered_after_steps)
        self.degraded_window_steps = int(degraded_window_steps)
        self.sse_poll_s = float(sse_poll_s)
        self.aggregator = CrossHostAggregator(local_host=self.host_id)
        self.port: Optional[int] = None
        self._server: Optional[_LiveHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    @classmethod
    def from_config(cls, lcfg, telemetry, watchdog=None, anomaly=None,
                    host_id: int = 0, step_fn=None,
                    steps_this_process_fn=None) -> "LiveObservabilityServer":
        """Build from a ``telemetry.live`` block (LiveTelemetryConfig)."""
        return cls(telemetry, port=lcfg.port, bind=lcfg.bind,
                   watchdog=watchdog, anomaly=anomaly, host_id=host_id,
                   step_fn=step_fn,
                   steps_this_process_fn=steps_this_process_fn,
                   recovered_after_steps=lcfg.recovered_after_steps,
                   degraded_window_steps=lcfg.degraded_window_steps,
                   sse_poll_s=lcfg.sse_poll_s)

    # ---------------------------------------------------------------- #
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "LiveObservabilityServer":
        if self._server is not None:
            return self
        self._stopping.clear()
        srv = _LiveHTTPServer((self.bind, self.requested_port), _LiveHandler)
        srv.telemetry = self.telemetry
        srv.aggregator = self.aggregator
        srv.watchdog = self.watchdog
        srv.anomaly = self.anomaly
        srv.host_id = self.host_id
        srv.last_step = self.step_fn
        srv.steps_this_process = self.steps_this_process_fn
        srv.recovered_after_steps = self.recovered_after_steps
        srv.degraded_window_steps = self.degraded_window_steps
        srv.sse_poll_s = self.sse_poll_s
        srv.stopping = self._stopping
        self._server = srv
        self.port = srv.server_address[1]
        self._thread = threading.Thread(
            target=srv.serve_forever, name="dstpu-live-server",
            kwargs={"poll_interval": 0.2}, daemon=True)
        self._thread.start()
        logger.info(f"live observability server on "
                    f"http://{self.bind}:{self.port} "
                    f"(/metrics /healthz /events /summary)")
        if self.telemetry is not None:
            self.telemetry.event("live_server_start", port=self.port,
                                 bind=self.bind)
            publish_elastic_gauges(self.telemetry.metrics)
        return self

    def stop(self) -> None:
        self._stopping.set()       # unblocks SSE followers
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
