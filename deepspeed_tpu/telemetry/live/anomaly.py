"""In-flight anomaly detection over the training loop's per-step signals.

Post-mortem telemetry tells you a multi-day run diverged *after* the tokens
are spent; this detector flags it at the step it happens.  Three checks, all
host-side and O(window):

  * **non-finite guard** — a NaN/Inf loss (or grad norm, when the caller has
    one) fires immediately; there is no baseline to consult because no
    finite history makes a non-finite loss acceptable;
  * **loss-spike z-score** — the current loss against the mean/std of a
    rolling window of recent finite losses.  Divergence usually starts as a
    spike orders of magnitude outside the band long before the loss goes
    non-finite;
  * **step-time regression** — the median of the newest few steps against
    the median of the older window.  A checkpoint-storage slowdown, a thermally
    throttled host, or an accidental recompile shows up here, not in loss.

Every incident emits a structured ``anomaly`` event, bumps the
``anomaly/events`` counter (labelled by type), and runs the configured
action: ``log`` (nothing more), ``checkpoint`` (a verified atomic
checkpoint through the fault subsystem, so the state *right at* the anomaly
is inspectable and restartable), or ``abort`` (checkpoint semantics are the
caller's — raise :class:`AnomalyAbort` from the training thread so the
elastic agent can restart from the last good tag).

A per-type cooldown keeps one bad regime from emitting an incident storm:
after firing, a type stays silent for ``cooldown_steps`` steps (the gauges
keep updating — only the incident/action path is suppressed).
"""
from __future__ import annotations

import math
import statistics
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ...utils.logging import logger

#: incident type names, also the ``type`` label on ``anomaly/events``
NONFINITE_LOSS = "nonfinite_loss"
NONFINITE_GRAD = "nonfinite_grad_norm"
LOSS_SPIKE = "loss_spike"
STEP_TIME_REGRESSION = "step_time_regression"


class AnomalyAbort(RuntimeError):
    """Raised from the training thread when an anomaly fires with
    ``action: "abort"``."""


class AnomalyDetector:
    """See module docstring.  ``action_target`` is anything with a
    ``save_checkpoint(dir, tag=...)`` method (the engine) — required for the
    ``checkpoint`` action, optional otherwise."""

    def __init__(self, action: str = "log", telemetry=None,
                 action_target: Any = None,
                 checkpoint_dir: str = "anomaly_checkpoints",
                 loss_window: int = 64, loss_zscore: float = 8.0,
                 min_steps: int = 8, step_time_window: int = 32,
                 step_time_threshold: float = 0.75,
                 step_time_recent: int = 3, step_time_min_s: float = 0.05,
                 cooldown_steps: int = 16):
        if action not in ("log", "checkpoint", "abort"):
            raise ValueError(f"anomaly action must be log|checkpoint|abort, "
                             f"got {action!r}")
        self.action = action
        self.telemetry = telemetry
        self.action_target = action_target
        self.checkpoint_dir = checkpoint_dir
        self.loss_zscore = float(loss_zscore)
        self.min_steps = max(int(min_steps), 2)
        self.step_time_threshold = float(step_time_threshold)
        self.step_time_recent = max(int(step_time_recent), 1)
        self.step_time_min_s = float(step_time_min_s)
        self.cooldown_steps = max(int(cooldown_steps), 0)
        # floors keyed on min_steps: a window the arming check can never
        # reach (AnomalyConfig validates this; direct constructions get the
        # clamp) would silently disable the detector for the whole run
        self._losses: "deque[float]" = deque(
            maxlen=max(int(loss_window), self.min_steps, 2))
        self._step_times: "deque[float]" = deque(
            maxlen=max(int(step_time_window),
                       self.min_steps + self.step_time_recent - 1,
                       self.step_time_recent + 2))
        self._cooldown_until: Dict[str, int] = {}
        self.incidents = 0
        self.last_incident_step: Optional[int] = None
        self.last_incident_type: Optional[str] = None

    # ---------------------------------------------------------------- #
    @classmethod
    def from_config(cls, acfg, telemetry=None,
                    action_target=None) -> "AnomalyDetector":
        """Build from a ``telemetry.live.anomaly`` block (AnomalyConfig)."""
        return cls(
            action=acfg.action, telemetry=telemetry,
            action_target=action_target,
            checkpoint_dir=acfg.checkpoint_dir,
            loss_window=acfg.loss_window, loss_zscore=acfg.loss_zscore,
            min_steps=acfg.min_steps,
            step_time_window=acfg.step_time_window,
            step_time_threshold=acfg.step_time_threshold,
            step_time_recent=acfg.step_time_recent,
            step_time_min_s=acfg.step_time_min_s,
            cooldown_steps=acfg.cooldown_steps,
        )

    # ---------------------------------------------------------------- #
    def observe(self, step: int, loss: Optional[float] = None,
                step_time_s: Optional[float] = None,
                grad_norm: Optional[float] = None) -> List[Dict[str, Any]]:
        """One post-step check.  Returns the incidents that fired (possibly
        empty).  ``action: "abort"`` raises :class:`AnomalyAbort` *after*
        recording every incident of the step."""
        incidents: List[Dict[str, Any]] = []
        step = int(step)

        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                incidents.append({"type": NONFINITE_LOSS, "loss": loss})
            else:
                z = self._loss_z(loss)
                if z is not None:
                    self._gauge("Anomaly/loss_zscore", z)
                    if z > self.loss_zscore:
                        incidents.append({
                            "type": LOSS_SPIKE, "loss": loss,
                            "zscore": round(z, 3),
                            "threshold": self.loss_zscore,
                            "window_mean": round(
                                statistics.fmean(self._losses), 6),
                        })
                self._losses.append(loss)

        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm):
                incidents.append({"type": NONFINITE_GRAD,
                                  "grad_norm": grad_norm})

        if step_time_s is not None and step_time_s > 0:
            check = self._step_time_ratio(float(step_time_s))
            if check is not None:
                ratio, baseline = check
                self._gauge("Anomaly/step_time_ratio", ratio)
                if ratio > 1.0 + self.step_time_threshold:
                    incidents.append({
                        "type": STEP_TIME_REGRESSION,
                        "step_time_s": round(float(step_time_s), 6),
                        "baseline_s": round(baseline, 6),
                        "ratio": round(ratio, 3),
                        "threshold": 1.0 + self.step_time_threshold,
                    })
            self._step_times.append(float(step_time_s))

        fired = [i for i in incidents if self._not_cooling(i["type"], step)]
        for incident in fired:
            self._record(step, incident)
        if fired:
            self._act(step, fired)
        return fired

    # ---------------------------------------------------------------- #
    def _loss_z(self, loss: float) -> Optional[float]:
        if len(self._losses) < self.min_steps:
            return None
        mean = statistics.fmean(self._losses)
        std = statistics.pstdev(self._losses)
        # a flat-lined window (std→0) would make any wiggle an anomaly;
        # floor the band at a fraction of the mean's magnitude
        std = max(std, 1e-3 * max(abs(mean), 1e-12))
        return (loss - mean) / std

    def _step_time_ratio(
            self, step_time_s: float) -> Optional[Tuple[float, float]]:
        """(ratio, baseline) or None while unarmed: ratio = (median of the
        newest ``recent`` incl. the current) / (baseline = median of the
        older window).  A step-CHANGE detector, medians on both sides: one
        slow step (a GC pause, a flush, an incidental recompile) cannot
        move the recent median, only a sustained shift can; the baseline
        median shrugs off prior spikes the same way.  Sub-``step_time_min_s``
        regimes are skipped outright — at millisecond step times the ratio
        is pure host noise (verified on the CPU sim, where a 3 ms step next
        to one 50 ms hiccup reads as a 6x \"regression\")."""
        history = list(self._step_times)
        older = history[:-(self.step_time_recent - 1) or None]
        recent = (history[len(older):] + [step_time_s])[-self.step_time_recent:]
        if len(older) < self.min_steps:
            return None
        baseline = statistics.median(older)
        if baseline <= 0 or baseline < self.step_time_min_s:
            return None          # regime too small to judge a ratio against
        return statistics.median(recent) / baseline, baseline

    def _not_cooling(self, kind: str, step: int) -> bool:
        until = self._cooldown_until.get(kind)
        if until is not None and step < until:
            return False
        self._cooldown_until[kind] = step + self.cooldown_steps + 1
        return True

    def _gauge(self, name: str, value: float) -> None:
        if self.telemetry is not None:
            self.telemetry.metrics.gauge(name).set(value)

    def _record(self, step: int, incident: Dict[str, Any]) -> None:
        self.incidents += 1
        self.last_incident_step = step
        self.last_incident_type = incident["type"]
        incident["step"] = step
        incident["action"] = self.action
        tel = self.telemetry
        if tel is not None:
            tel.metrics.counter("anomaly/events").inc(type=incident["type"])
            tel.metrics.gauge("Anomaly/last_step").set(step)
            tel.event("anomaly", **incident)
        logger.warning(f"ANOMALY at step {step}: {incident}")

    def _act(self, step: int, incidents: List[Dict[str, Any]]) -> None:
        if self.action == "checkpoint":
            self._checkpoint(step, incidents)
        elif self.action == "abort":
            if self.telemetry is not None:
                # the process is about to unwind — make the incident durable
                try:
                    self.telemetry.flush()
                except Exception as e:  # noqa: BLE001 — abort still happens
                    logger.warning(f"anomaly flush before abort failed: {e!r}")
            raise AnomalyAbort(
                f"anomaly at step {step}: "
                + "; ".join(i["type"] for i in incidents))

    def _checkpoint(self, step: int, incidents: List[Dict[str, Any]]) -> None:
        """``action: "checkpoint"`` — verified atomic commit via the fault
        subsystem (engine.save_checkpoint → OrbaxCheckpointEngine manifest/
        tmp+fsync+replace).  Failure is logged, never raised: the action is
        forensics, not control flow."""
        if self.action_target is None:
            logger.warning("anomaly action=checkpoint but no action_target "
                           "wired; skipping")
            return
        tag = f"anomaly_step{step}"
        try:
            self.action_target.save_checkpoint(
                self.checkpoint_dir, tag=tag,
                client_state={"anomaly": incidents})
            if self.telemetry is not None:
                self.telemetry.event("anomaly_checkpoint", step=step, tag=tag,
                                     dir=self.checkpoint_dir)
        except Exception as e:  # noqa: BLE001 — see docstring
            logger.error(f"anomaly checkpoint at step {step} failed: {e!r}")
            if self.telemetry is not None:
                self.telemetry.event("anomaly_checkpoint_failed", step=step,
                                     tag=tag, error=repr(e))
