"""Live observability plane: in-flight HTTP endpoints, cross-host
aggregation, and anomaly detection over the telemetry hub.

Enable via ``telemetry.live`` (see ``runtime/config.py``):

    {"telemetry": {"enabled": true,
                   "live": {"enabled": true, "port": 8790}}}

then, during the run:  ``curl :8790/healthz`` / ``/metrics`` / ``/summary``
or ``curl -N :8790/events`` for the SSE tail.
"""
from .aggregator import (CrossHostAggregator, SnapshotPusher,
                         collect_snapshot, push_snapshot)
from .anomaly import AnomalyAbort, AnomalyDetector
from .server import (LiveObservabilityServer, elastic_state_from_env,
                     health_report, live_summary, publish_elastic_gauges)

__all__ = [
    "AnomalyAbort", "AnomalyDetector", "CrossHostAggregator",
    "LiveObservabilityServer", "SnapshotPusher", "collect_snapshot",
    "elastic_state_from_env", "health_report", "live_summary",
    "publish_elastic_gauges", "push_snapshot",
]
