"""Goodput ledger: attribute every wall-second to an exhaustive category set.

The fleet can trace one request end-to-end (``telemetry/tracing``) and
roofline one kernel (``profiling/roofline.py``), but neither answers the
question a candidate config is ultimately judged by: *what fraction of the
last hour was useful work?*  This module keeps that book.  A
:class:`GoodputLedger` attributes elapsed wall time, per process, to a
closed, non-overlapping category set (:data:`CATEGORIES`):

  ``compute``            useful device work — training step math after the
                         exposed-comm share is removed (engine
                         ``_post_step_logging``), serving decode/verify
                         windows and non-recompute prefill chunks
                         (``lifecycle._apply_window_results`` /
                         ``_run_prefill``)
  ``exposed_comm``       collective time NOT hidden behind compute:
                         step wall x the overlap manager's measured
                         ``exposed_comm_fraction``
  ``compile``            first-use XLA traces: step 1 of ``train_batch``,
                         compile-polluted serving windows
  ``host_sync``          host-side per-step bookkeeping (the
                         ``_post_step_logging`` body itself: monitors,
                         heartbeats, anomaly/straggler detection)
  ``checkpoint``         ``save_checkpoint`` wall time
  ``preempt_recompute``  prefill chunks replaying tokens a KV-pressure
                         preemption already produced once (riders with a
                         resume seed)
  ``drain``              drain-loop residual: wall spent in
                         ``LifecycleScheduler.drain`` beyond the windows'
                         own compute attribution
  ``shed``               admission-rejection handling, tenant-attributed
                         (lifecycle queue_full/draining sheds, router QoS
                         sheds riding the PR-16 tenant labels)
  ``restart``            elastic-agent restart gaps (backoff + respawn)
  ``idle``               explicitly recorded waits (the serving driver's
                         empty-queue sleep) PLUS the derived remainder —
                         wall time nothing claimed

**Conservation contract.**  ``idle`` absorbs the unattributed remainder,
so the reported categories always sum to the measured wall *unless* the
instrumentation double-counts: attributing more seconds than actually
elapsed surfaces as ``overcommit_s > 0`` and :meth:`conserved` fails once
overcommit exceeds ``eps x wall``.  Leaks (a seam that should attribute
but doesn't) surface as ``idle`` inflation — the chaos/conservation tests
pin both directions by asserting every *expected* category lands > 0 and
the sum conserves.

Install pattern mirrors the trace store: process-global instance via
:func:`install_goodput_ledger` / :func:`get_goodput_ledger`, ``None`` IS
the disabled fast path, and every instrumentation site goes through
:func:`record_goodput` / :func:`goodput_residual` which no-op on one
global read when disabled.

Fleet rollup: a replica serializes :meth:`snapshot` into its ``/healthz``
body; the router scrapes them and :func:`rollup` sums walls, categories
and tenant-attributed shed time into one fleet-level snapshot (the
``goodput`` section of the router's ``/healthz``) — the scalar
``goodput_fraction`` there is the score ``dstpu-replay`` and the autotuner
judge configs by.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional

#: the closed category axis — exhaustive and non-overlapping by contract;
#: instrumentation sites MUST pick exactly one per elapsed interval
CATEGORIES = ("compute", "exposed_comm", "compile", "host_sync",
              "checkpoint", "preempt_recompute", "drain", "shed",
              "restart", "idle")


class GoodputLedger:
    """Per-process wall-time accounting over :data:`CATEGORIES`.

    ``clock`` is injectable for tests and must be monotonic; the epoch is
    taken at construction (or the last :meth:`reset`), so ``wall_s`` is
    "seconds this ledger has existed" and the conservation invariant is
    judged against that window.
    """

    def __init__(self, component: str = "proc",
                 clock=time.monotonic) -> None:
        self.component = component
        self.clock = clock
        self._lock = threading.Lock()
        self._epoch = clock()
        self._cats: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._attr_total = 0.0
        self._tenant_shed: Dict[str, float] = {}

    # ------------------------------------------------------------------ #
    # Recording
    # ------------------------------------------------------------------ #
    def add(self, category: str, seconds: float,
            tenant: Optional[str] = None) -> None:
        """Attribute ``seconds`` of wall time to ``category``.

        Raises on an unknown category — a typo'd attribution site must
        fail loudly, not silently open an eleventh bucket the
        conservation tests don't know about.
        """
        if category not in self._cats:
            raise ValueError(f"unknown goodput category {category!r} "
                             f"(must be one of {CATEGORIES})")
        s = float(seconds)
        if s <= 0.0:
            return
        with self._lock:
            self._cats[category] += s
            self._attr_total += s
            if tenant is not None and category == "shed":
                self._tenant_shed[str(tenant)] = \
                    self._tenant_shed.get(str(tenant), 0.0) + s

    @contextlib.contextmanager
    def residual_block(self, category: str,
                       tenant: Optional[str] = None) -> Iterator[None]:
        """Attribute the block's elapsed wall MINUS any attributions made
        inside it to ``category`` — the envelope pattern that keeps e.g. a
        drain loop non-overlapping with the decode windows it runs (their
        walls land in ``compute``; only the loop's own overhead lands in
        ``drain``).  Single-threaded envelopes only: attributions from
        OTHER threads during the block are subtracted too.
        """
        t0 = self.clock()
        with self._lock:
            a0 = self._attr_total
        try:
            yield
        finally:
            elapsed = self.clock() - t0
            with self._lock:
                inner = self._attr_total - a0
            self.add(category, elapsed - inner, tenant=tenant)

    def reset(self) -> None:
        """Zero the books and restart the wall epoch."""
        with self._lock:
            self._epoch = self.clock()
            self._cats = {c: 0.0 for c in CATEGORIES}
            self._attr_total = 0.0
            self._tenant_shed.clear()

    # ------------------------------------------------------------------ #
    # Reading
    # ------------------------------------------------------------------ #
    def wall_s(self) -> float:
        return max(0.0, self.clock() - self._epoch)

    def attributed_s(self) -> float:
        with self._lock:
            return self._attr_total

    def overcommit_s(self) -> float:
        """Seconds attributed BEYOND the measured wall — the
        double-counting detector.  0 when the books balance."""
        return max(0.0, self.attributed_s() - self.wall_s())

    def conserved(self, eps: float = 0.01) -> bool:
        """True iff categories (with derived idle) sum to the measured
        wall within ``eps`` x wall.  With idle absorbing the remainder the
        only way to break conservation is overcommit."""
        wall = self.wall_s()
        return self.overcommit_s() <= eps * max(wall, 1e-9)

    def snapshot(self) -> Dict[str, Any]:
        """The serializable per-process view: every category (idle
        includes the derived remainder), fractions of wall, the goodput
        scalar, the overcommit detector and tenant-attributed shed."""
        wall = self.wall_s()
        with self._lock:
            cats = dict(self._cats)
            attr = self._attr_total
            tenants = dict(self._tenant_shed)
        slack = wall - attr
        cats["idle"] += max(0.0, slack)
        denom = max(wall, 1e-9)
        return {
            "component": self.component,
            "wall_s": round(wall, 6),
            "categories": {c: round(v, 6) for c, v in cats.items()},
            "fractions": {c: round(v / denom, 6) for c, v in cats.items()},
            "goodput_fraction": round(cats["compute"] / denom, 6),
            "overcommit_s": round(max(0.0, -slack), 6),
            "tenant_shed_s": {t: round(v, 6)
                              for t, v in sorted(tenants.items())},
            "conserved": self.conserved(),
        }

    # ------------------------------------------------------------------ #
    # Registry surface
    # ------------------------------------------------------------------ #
    def publish(self) -> None:
        """Mirror the snapshot into ``goodput/*`` registry gauges (and
        per-tenant ``goodput/tenant_shed_s`` labelled gauges); no-op when
        telemetry is off."""
        from .hub import get_telemetry

        tel = get_telemetry()
        if tel is None:
            return
        snap = self.snapshot()
        m = tel.metrics
        m.gauge("goodput/wall_s").set(snap["wall_s"])
        for cat, v in snap["categories"].items():
            m.gauge(f"goodput/{cat}_s").set(v)
        m.gauge("goodput/goodput_fraction").set(snap["goodput_fraction"])
        m.gauge("goodput/overcommit_s").set(snap["overcommit_s"])
        for tenant, v in snap["tenant_shed_s"].items():
            m.gauge("goodput/tenant_shed_s").set(v, tenant=tenant)


def rollup(snapshots: Iterable[Optional[Dict[str, Any]]],
           component: str = "fleet") -> Dict[str, Any]:
    """Sum per-process snapshots (e.g. scraped replica ``/healthz``
    bodies + the router's own ledger) into one fleet-level snapshot.
    Tolerant of None / malformed entries — a half-scraped replica must
    degrade the rollup, never kill ``/healthz``."""
    wall = 0.0
    over = 0.0
    n = 0
    cats: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
    tenants: Dict[str, float] = {}
    for s in snapshots:
        if not isinstance(s, dict):
            continue
        n += 1
        try:
            wall += float(s.get("wall_s") or 0.0)
            over += float(s.get("overcommit_s") or 0.0)
            for c in CATEGORIES:
                cats[c] += float((s.get("categories") or {}).get(c) or 0.0)
            for t, v in (s.get("tenant_shed_s") or {}).items():
                tenants[str(t)] = tenants.get(str(t), 0.0) + float(v)
        except (TypeError, ValueError):
            continue
    denom = max(wall, 1e-9)
    return {
        "component": component,
        "processes": n,
        "wall_s": round(wall, 6),
        "categories": {c: round(v, 6) for c, v in cats.items()},
        "fractions": {c: round(v / denom, 6) for c, v in cats.items()},
        "goodput_fraction": round(cats["compute"] / denom, 6),
        "overcommit_s": round(over, 6),
        "tenant_shed_s": {t: round(v, 6) for t, v in sorted(tenants.items())},
        "conserved": over <= 0.01 * denom,
    }


# --------------------------------------------------------------------- #
# Process-global instance (trace-store install pattern)
# --------------------------------------------------------------------- #
_GLOBAL: Optional[GoodputLedger] = None
_GLOBAL_LOCK = threading.Lock()


def install_goodput_ledger(ledger: Optional[GoodputLedger]
                           ) -> Optional[GoodputLedger]:
    """Install (or clear, with None) the process-global goodput ledger."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        previous, _GLOBAL = _GLOBAL, ledger
    return previous


def get_goodput_ledger() -> Optional[GoodputLedger]:
    return _GLOBAL


def record_goodput(category: str, seconds: float,
                   tenant: Optional[str] = None) -> None:
    """Attribute ``seconds`` to ``category`` on the installed ledger;
    no-op (one global read) when accounting is disabled."""
    ledger = _GLOBAL
    if ledger is not None:
        ledger.add(category, seconds, tenant=tenant)


def goodput_residual(category: str, tenant: Optional[str] = None):
    """:meth:`GoodputLedger.residual_block` on the installed ledger, or a
    nullcontext when accounting is disabled."""
    ledger = _GLOBAL
    if ledger is None:
        return contextlib.nullcontext()
    return ledger.residual_block(category, tenant=tenant)


#: package-level re-export names (``CATEGORIES``/``rollup`` are too
#: generic to live un-prefixed in ``deepspeed_tpu.telemetry``)
GOODPUT_CATEGORIES = CATEGORIES
rollup_goodput = rollup
