"""Structured event log: append-only JSONL with an in-memory ring mirror.

Every telemetry record — spans, metric snapshots, checkpoint lifecycle,
fault/watchdog incidents, memory samples — flows through here as one JSON
object per line, so a single ``events.jsonl`` fully describes a run and the
``dstpu-telemetry`` CLI (or any jq pipeline) can reconstruct it offline.

Write-through semantics: events are flushed to disk as they are emitted
(line-buffered + explicit flush) because the most interesting events are the
ones right before a crash.  Event volume is low (per step / per incident,
never per op dispatch), so durability wins over batching here.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


def _jsonable(obj):
    """json.dumps ``default`` shared by the event log and checkpoint
    meta.json: numpy/jax scalars → Python scalars, arrays → lists,
    set/tuple → list, everything else → str."""
    if hasattr(obj, "item"):        # 0-d numpy/jax scalar
        try:
            return obj.item()
        except Exception:
            pass                    # multi-element array: fall through
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            pass
    if isinstance(obj, (set, tuple)):
        return list(obj)
    return str(obj)


class EventLog:
    def __init__(self, path: Optional[str] = None, max_memory: int = 10_000):
        self.path = path
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=int(max_memory))
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    # ---------------------------------------------------------------- #
    def emit(self, kind: str, **fields) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"ts": time.time(), "kind": str(kind)}
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, default=_jsonable) + "\n")
                    self._fh.flush()
                except (OSError, ValueError):
                    pass  # a full/closed disk must not kill the training loop
        return rec

    def recent(self, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e.get("kind") == kind]
        return events[-n:] if n else events

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except (OSError, ValueError):
                    pass
                self._fh = None


def read_jsonl(path: str) -> Iterator[Dict[str, Any]]:
    """Parse an events.jsonl, skipping torn/corrupt lines (a crashed writer
    may leave a partial last line — the rest of the log is still good)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue
