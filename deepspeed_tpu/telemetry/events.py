"""Structured event log: append-only JSONL with an in-memory ring mirror.

Every telemetry record — spans, metric snapshots, checkpoint lifecycle,
fault/watchdog incidents, memory samples — flows through here as one JSON
object per line, so a single ``events.jsonl`` fully describes a run and the
``dstpu-telemetry`` CLI (or any jq pipeline) can reconstruct it offline.

Write-through semantics: events are flushed to disk as they are emitted
(line-buffered + explicit flush) because the most interesting events are the
ones right before a crash.  Event volume is low (per step / per incident,
never per op dispatch), so durability wins over batching here.

Disk growth is bounded: with ``max_bytes`` set, the log rotates logrotate-
style (``events.jsonl`` → ``events.jsonl.1`` → ``.2`` …, keep-last-``keep``)
so a week-long run can't fill the volume; :func:`read_event_segments` walks
the rotated segments oldest-first so readers still see one ordered stream.

The log also carries a monotonic cursor (events ever emitted) so live
followers — the ``/events`` SSE endpoint — can poll for "everything since
my last read" against the ring without re-reading the file.
"""
from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple


def _jsonable(obj):
    """json.dumps ``default`` shared by the event log and checkpoint
    meta.json: numpy/jax scalars → Python scalars, arrays → lists,
    set/tuple → list, everything else → str."""
    if hasattr(obj, "item"):        # 0-d numpy/jax scalar
        try:
            return obj.item()
        except Exception:
            pass                    # multi-element array: fall through
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            pass
    if isinstance(obj, (set, tuple)):
        return list(obj)
    return str(obj)


class EventLog:
    def __init__(self, path: Optional[str] = None, max_memory: int = 10_000,
                 max_bytes: int = 0, keep: int = 3):
        self.path = path
        #: rotate the JSONL past this many bytes (0 = never rotate)
        self.max_bytes = int(max_bytes)
        #: rotated segments retained (``.1`` newest … ``.keep`` oldest)
        self.keep = max(int(keep), 1)
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=int(max_memory))
        self._total = 0              # events ever emitted (SSE cursor)
        self._fh = None
        self._closed = False         # close() is final; a lost fh is not
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    # ---------------------------------------------------------------- #
    def emit(self, kind: str, **fields) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"ts": time.time(), "kind": str(kind)}
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            self._total += 1
            if self._fh is None and self.path and not self._closed:
                # the handle was lost (a rotation reopen failed on a full
                # disk) — keep trying, conditions like ENOSPC clear
                try:
                    self._fh = open(self.path, "a", buffering=1)
                except OSError:
                    self._fh = None
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, default=_jsonable) + "\n")
                    self._fh.flush()
                    if self.max_bytes and self._fh.tell() >= self.max_bytes:
                        self._rotate_locked()
                except (OSError, ValueError):
                    pass  # a full/closed disk must not kill the training loop
        return rec

    def _rotate_locked(self) -> None:
        """Shift ``path`` → ``path.1`` → … → ``path.keep`` (oldest dropped)
        and reopen a fresh live file.  Caller holds the lock; every step is
        best-effort, and a failed reopen leaves ``_fh = None`` for
        :meth:`emit` to retry — rotation must never permanently kill the
        crash-forensics log."""
        try:
            self._fh.close()
        except (OSError, ValueError):
            pass
        self._fh = None
        try:
            oldest = f"{self.path}.{self.keep}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            pass
        try:
            self._fh = open(self.path, "a", buffering=1)
        except OSError:
            self._fh = None          # emit() retries the reopen

    def recent(self, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e.get("kind") == kind]
        return events[-n:] if n else events

    def cursor(self) -> int:
        """Monotonic count of events ever emitted (for events_since)."""
        with self._lock:
            return self._total

    def tail(self, n: int) -> Tuple[List[Dict[str, Any]], int]:
        """The newest ``n`` ring events AND the cursor just past them, read
        under one lock — an SSE follower replaying then following must not
        see an event land between the two reads and get it twice."""
        with self._lock:
            ring = list(self._ring)
            return (ring[-n:] if n else []), self._total

    def events_since(self, cursor: int) -> Tuple[List[Dict[str, Any]], int]:
        """Events emitted after ``cursor`` (a prior :meth:`cursor` /
        ``events_since`` return) and the new cursor, read atomically.
        Events older than the ring window are gone — a slow follower just
        resumes from what's retained (it is a tail, not a replay log)."""
        with self._lock:
            total = self._total
            n_new = total - int(cursor)
            if n_new <= 0:
                return [], total
            ring = list(self._ring)
            return ring[-min(n_new, len(ring)):], total

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except (OSError, ValueError):
                    pass
                self._fh = None


def read_jsonl(path: str) -> Iterator[Dict[str, Any]]:
    """Parse an events.jsonl, skipping torn/corrupt lines (a crashed writer
    may leave a partial last line — the rest of the log is still good)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue


def event_segments(path: str) -> List[str]:
    """All on-disk segments of a (possibly rotated) event log, oldest first:
    ``path.N`` … ``path.2``, ``path.1``, then the live ``path``."""
    out: List[str] = []
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    if os.path.isdir(d):
        rotated = []
        for fn in os.listdir(d):
            m = pat.match(fn)
            if m:
                rotated.append((int(m.group(1)), os.path.join(d, fn)))
        out.extend(p for _, p in sorted(rotated, reverse=True))
    if os.path.exists(path):
        out.append(path)
    return out


def read_event_segments(path: str) -> Iterator[Dict[str, Any]]:
    """Like :func:`read_jsonl`, but across rotation: yields the full ordered
    stream from every retained segment (oldest rotated file first)."""
    for seg in event_segments(path):
        yield from read_jsonl(seg)
