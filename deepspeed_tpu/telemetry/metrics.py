"""Metrics registry: counters, gauges, and histograms with labels.

One process-wide aggregation point for everything the framework measures —
step times, collective message sizes and bandwidths, memory samples, fault
counters, monitor scalars.  Consumers:

  * ``snapshot()`` — list of plain dicts, one per (metric, labelset) series,
    written as ``kind: "metric"`` lines into the telemetry JSONL log;
  * ``prometheus_text()`` — Prometheus text-exposition rendering for
    scrape-style integration (written as ``metrics.prom`` on flush).

Histograms keep exact count/sum/min/max plus a bounded uniform reservoir of
samples for percentiles (`p50/p90/p95/p99`) — memory stays O(cap) no matter
how many observations arrive, and the reservoir keeps every observation
equally likely to be retained (Vitter's algorithm R).

Consistency: every write, every reader accessor, and both exports run under
the one registry lock, so a concurrent scrape (the live ``/metrics``
endpoint polls mid-step) can never observe a half-written histogram
reservoir or a count/sum pair torn across an update.
"""
from __future__ import annotations

import random
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_SANITIZE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_labels(key: LabelKey, extra: Iterable[Tuple[str, str]] = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{_prom_name(k)}="{v}"' for k, v in items)
    return "{" + body + "}"


class _CounterSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _GaugeSeries:
    __slots__ = ("value", "vmin", "vmax", "count")

    def __init__(self):
        self.value = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.count = 0


class _HistogramSeries:
    __slots__ = ("count", "total", "vmin", "vmax", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples: List[float] = []


class Metric:
    kind = "abstract"

    def __init__(self, name: str, registry: "MetricsRegistry"):
        self.name = name
        self._registry = registry
        self._series: Dict[LabelKey, Any] = {}

    def _get(self, labels: Dict[str, Any], factory):
        key = _label_key(labels)
        series = self._series.get(key)
        if series is None:
            series = self._series.setdefault(key, factory())
        return series

    def labelsets(self) -> List[LabelKey]:
        with self._registry._lock:
            return list(self._series.keys())


class Counter(Metric):
    kind = "counter"

    def inc(self, n: float = 1.0, **labels) -> None:
        with self._registry._lock:
            self._get(labels, _CounterSeries).value += n

    def value(self, **labels) -> float:
        with self._registry._lock:
            series = self._series.get(_label_key(labels))
            return series.value if series else 0.0

    def total(self) -> float:
        """Sum over every labelset — e.g. all ``fault/events`` regardless of
        the ``name`` label (the /healthz incident counts)."""
        with self._registry._lock:
            return sum(s.value for s in self._series.values())


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._registry._lock:
            s = self._get(labels, _GaugeSeries)
            value = float(value)
            s.value = value
            s.count += 1
            if value < s.vmin:
                s.vmin = value
            if value > s.vmax:
                s.vmax = value

    def value(self, **labels) -> Optional[float]:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            return s.value if s else None

    def high_water(self, **labels) -> Optional[float]:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            return s.vmax if s and s.count else None


class Histogram(Metric):
    kind = "histogram"

    def observe(self, value: float, **labels) -> None:
        reg = self._registry
        with reg._lock:
            s = self._get(labels, _HistogramSeries)
            value = float(value)
            s.count += 1
            s.total += value
            if value < s.vmin:
                s.vmin = value
            if value > s.vmax:
                s.vmax = value
            cap = reg.histogram_max_samples
            if len(s.samples) < cap:
                s.samples.append(value)
            else:  # reservoir: replace a uniform victim so old samples decay
                j = reg._rng.randrange(s.count)
                if j < cap:
                    s.samples[j] = value

    def percentile(self, q: float, **labels) -> Optional[float]:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            if s is None or not s.samples:
                return None
            svals = sorted(s.samples)
        return _percentile(svals, q)

    def count(self, **labels) -> int:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            return s.count if s else 0

    def sum(self, **labels) -> float:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            return s.total if s else 0.0

    def mean(self, **labels) -> Optional[float]:
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s.count == 0:
                return None
            return s.total / s.count


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile, q in [0, 100]."""
    if not sorted_vals:
        raise ValueError("empty sample set")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


_QUANTILES = (50.0, 90.0, 95.0, 99.0)


class MetricsRegistry:
    def __init__(self, histogram_max_samples: int = 4096, seed: int = 0):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Metric] = {}
        self.histogram_max_samples = int(histogram_max_samples)
        self._rng = random.Random(seed)

    # ---------------------------------------------------------------- #
    def _get_or_create(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, self)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics.keys())

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def gauge_values(self) -> Dict[str, float]:
        """Label-free gauge name → current value, in one lock hold.  Far
        cheaper than :meth:`snapshot` (no histogram reservoir sorts under
        the lock) — the live snapshot pusher polls this every push
        interval on every host, right beside the training thread's metric
        writes."""
        out: Dict[str, float] = {}
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if isinstance(m, Gauge):
                    series = m._series.get(())   # label-free labelset key
                    if series is not None:
                        out[name] = series.value
        return out

    # ---------------------------------------------------------------- #
    def snapshot(self) -> List[Dict[str, Any]]:
        """One dict per (metric, labelset) series — JSONL-ready."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                for key, s in sorted(m._series.items()):
                    row: Dict[str, Any] = {"name": name, "type": m.kind,
                                           "labels": dict(key)}
                    if m.kind == "counter":
                        row["value"] = s.value
                    elif m.kind == "gauge":
                        row.update(value=s.value, min=s.vmin, max=s.vmax,
                                   count=s.count)
                    else:
                        row.update(count=s.count, sum=s.total)
                        if s.count:
                            row.update(min=s.vmin, max=s.vmax,
                                       mean=s.total / s.count)
                            svals = sorted(s.samples)
                            for q in _QUANTILES:
                                row[f"p{q:g}"] = _percentile(svals, q)
                    out.append(row)
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format snapshot."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                pname = _prom_name(name)
                lines.append(f"# TYPE {pname} "
                             f"{'summary' if m.kind == 'histogram' else m.kind}")
                for key, s in sorted(m._series.items()):
                    if m.kind == "counter":
                        lines.append(f"{pname}{_prom_labels(key)} {s.value:g}")
                    elif m.kind == "gauge":
                        lines.append(f"{pname}{_prom_labels(key)} {s.value:g}")
                        if s.count:
                            lines.append(
                                f"{pname}_max{_prom_labels(key)} {s.vmax:g}")
                    else:
                        lines.append(f"{pname}_count{_prom_labels(key)} {s.count}")
                        lines.append(f"{pname}_sum{_prom_labels(key)} {s.total:g}")
                        if s.samples:
                            svals = sorted(s.samples)
                            for q in _QUANTILES:
                                lab = _prom_labels(
                                    key, [("quantile", f"{q / 100.0:g}")])
                                lines.append(
                                    f"{pname}{lab} {_percentile(svals, q):g}")
        return "\n".join(lines) + ("\n" if lines else "")
