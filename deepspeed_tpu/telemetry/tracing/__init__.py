"""Fleet-wide request tracing: end-to-end span timelines from router
admission to decode window.

A :class:`TraceContext` (W3C-traceparent wire form) is minted at the first
hop and propagated through every subsequent one; each process appends
typed spans to its process-global :class:`RequestTraceStore` and returns
them in-band with HTTP responses so the router owns the fleet-merged
view.  Tail-based sampling keeps flagged/slow/exemplar traces and samples
the steady state; ``bin/dstpu-trace`` renders waterfalls and Chrome-trace
exports offline, ``GET /traces`` serves the live view.  See the README
"Request tracing" runbook.
"""
from .context import RETURN_SPANS_FIELD, TRACE_HEADER, TraceContext
from .store import (
    ALWAYS_KEEP_FLAGS,
    FLAG_BY_REASON,
    SPAN_KINDS,
    RequestTraceStore,
    flag_trace,
    get_trace_store,
    install_trace_store,
    merge_trace,
    record_span,
    span_coverage,
    trace_id_of,
    traces_endpoint_payload,
)

__all__ = [
    "ALWAYS_KEEP_FLAGS", "FLAG_BY_REASON", "RETURN_SPANS_FIELD",
    "SPAN_KINDS", "TRACE_HEADER",
    "RequestTraceStore", "TraceContext", "flag_trace", "get_trace_store",
    "install_trace_store", "merge_trace", "record_span", "span_coverage",
    "trace_id_of", "traces_endpoint_payload",
]
