"""W3C-traceparent-style request-trace context.

One :class:`TraceContext` is minted per request at the FIRST hop that sees
it — router admission (``dstpu-router``) or the serving front end
(``dstpu-serve``) for direct requests — and propagated through every
subsequent hop so each process can append typed spans under one fleet-wide
trace id.  The wire form is exactly the W3C ``traceparent`` header
(https://www.w3.org/TR/trace-context/):

    00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>

carried BOTH as an HTTP header (``traceparent``) and as a JSON body field
of the same name — the router forwards the body field so replicas behind
any proxy still see it, and curl users can opt a request into an existing
trace without header plumbing.  Flag bit 0 is the W3C ``sampled`` hint;
tail-based sampling (store.py) makes the real keep/drop decision at trace
completion, so the hint only seeds the default.
"""
from __future__ import annotations

import dataclasses
import re
import uuid
from typing import Optional

#: HTTP header AND JSON body field carrying the context between hops
TRACE_HEADER = "traceparent"

#: JSON body marker an upstream MERGING hop (the router) stamps next to
#: the context: "return your finished spans in-band — I will merge and
#: strip them".  External clients that merely JOIN a trace (curl with a
#: traceparent) don't set it and get just the trace id back, never the
#: internal span dump.
RETURN_SPANS_FIELD = "trace_return_spans"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    trace_id: str          # 32 lowercase hex chars
    span_id: str           # 16 lowercase hex chars (this hop's parent id)
    flags: int = 1         # bit 0 = sampled hint
    #: True when this context arrived over the wire (header/body) rather
    #: than being minted locally.  Not part of the wire format and
    #: excluded from equality.
    adopted: bool = dataclasses.field(default=False, compare=False)
    #: True when the sender also stamped RETURN_SPANS_FIELD — an upstream
    #: MERGING hop (the router) exists that consumes in-band span
    #: payloads.  Adopted alone is NOT enough: an external client joining
    #: a trace is adopted too, and must not receive the span dump.
    return_spans: bool = dataclasses.field(default=False, compare=False)

    @classmethod
    def mint(cls, sampled: bool = True) -> "TraceContext":
        return cls(trace_id=uuid.uuid4().hex,
                   span_id=uuid.uuid4().hex[:16],
                   flags=1 if sampled else 0)

    @classmethod
    def parse(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Parse a ``traceparent`` value; None on anything malformed (a
        bad client header must never break admission — the hop just mints
        a fresh context instead)."""
        if not header or not isinstance(header, str):
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        if set(m.group(1)) == {"0"} or set(m.group(2)) == {"0"}:
            # all-zero ids are INVALID per the W3C spec — the classic
            # broken-propagation artifact; adopting them would collapse
            # every such client into one shared trace
            return None
        try:
            flags = int(m.group(3), 16)
        except ValueError:  # pragma: no cover — regex already guards
            return None
        return cls(trace_id=m.group(1), span_id=m.group(2), flags=flags,
                   adopted=True)

    @classmethod
    def from_request(cls, headers, payload: Optional[dict] = None
                     ) -> "TraceContext":
        """Resolve the context for an incoming HTTP request: the
        ``traceparent`` header wins, then the JSON body field, else a
        fresh mint.  ``headers`` is any ``.get``-able mapping (the stdlib
        ``BaseHTTPRequestHandler.headers`` qualifies)."""
        ctx = None
        if headers is not None:
            ctx = cls.parse(headers.get(TRACE_HEADER))
        if ctx is None and payload:
            ctx = cls.parse(payload.get(TRACE_HEADER))
        if ctx is None:
            return cls.mint()
        if payload and payload.get(RETURN_SPANS_FIELD):
            ctx = dataclasses.replace(ctx, return_spans=True)
        return ctx

    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags & 0xff:02x}"

    def child(self) -> "TraceContext":
        """Same trace, fresh parent span id — the value a hop forwards
        downstream."""
        return TraceContext(trace_id=self.trace_id,
                            span_id=uuid.uuid4().hex[:16],
                            flags=self.flags)

    @property
    def sampled(self) -> bool:
        return bool(self.flags & 1)
