"""``bin/dstpu-trace``: per-request waterfalls over a traces.jsonl.

Reads the ``traces.jsonl`` a :class:`~.store.RequestTraceStore` writes
(rotation-aware, one ``kind: "trace"`` line per kept trace) and renders:

  * default          — store overview: trace counts, the fleet-merged
    per-segment TTFT/TPOT decomposition (count / total / p50 / p95), and
    the slowest-traces table;
  * ``--slowest N``  — the N slowest traces with per-segment sums;
  * ``--request ID`` — one request's waterfall: every typed span on a
    shared timeline (offset / duration / component / bar), plus the
    work-segment coverage of the request wall;
  * ``--chrome OUT`` — fleet-merged Chrome-trace export through
    ``telemetry/trace.py``'s exporter (``chrome://tracing`` / Perfetto):
    components map to threads, span attrs ride ``args``.

``PATH`` is a telemetry output dir (containing ``traces.jsonl``) or a
traces.jsonl path.  ``--request`` accepts a unique trace-id prefix.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from ..events import read_event_segments
from ..metrics import _percentile
from .store import span_coverage

TRACES_FILE = "traces.jsonl"


def load_traces(path: str) -> List[Dict[str, Any]]:
    """All trace records from a dir or jsonl path, de-duplicated by trace
    id (the newest line wins — a re-finish can re-emit)."""
    if os.path.isdir(path):
        path = os.path.join(path, TRACES_FILE)
    by_id: "Dict[str, Dict[str, Any]]" = {}
    for rec in read_event_segments(path):
        if rec.get("kind") != "trace" or not rec.get("trace"):
            continue
        by_id[str(rec["trace"])] = rec
    return list(by_id.values())


def find_trace(traces: Sequence[Dict[str, Any]],
               wanted: str) -> Optional[Dict[str, Any]]:
    matches = [t for t in traces if str(t["trace"]).startswith(wanted)]
    if len(matches) > 1:
        exact = [t for t in matches if str(t["trace"]) == wanted]
        return exact[0] if exact else None
    return matches[0] if matches else None


# --------------------------------------------------------------------- #
# Rendering
# --------------------------------------------------------------------- #
def segment_table(traces: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    durs: Dict[str, List[float]] = {}
    for t in traces:
        for s in t.get("spans") or []:
            durs.setdefault(str(s.get("kind", "?")), []).append(
                float(s.get("dur_s", 0.0)))
    rows = []
    for kind, vals in durs.items():
        svals = sorted(vals)
        rows.append({"segment": kind, "count": len(vals),
                     "total_s": sum(vals),
                     "p50_s": _percentile(svals, 50),
                     "p95_s": _percentile(svals, 95)})
    rows.sort(key=lambda r: r["total_s"], reverse=True)
    return rows


def segment_table_lines(rows: Sequence[Dict[str, Any]]) -> List[str]:
    """THE per-segment decomposition table — shared by this CLI's
    overview and dstpu-telemetry's 'request tracing' section so the two
    renderings cannot drift.  Rows: segment/count/total_s/p50_s/p95_s
    (seconds), pre-sorted by the caller."""
    out = [f"{'segment':<18}{'count':>7}{'total(ms)':>12}{'p50(ms)':>10}"
           f"{'p95(ms)':>10}"]
    for r in rows:
        out.append(f"{r['segment']:<18}{int(r['count'] or 0):>7}"
                   f"{(r['total_s'] or 0) * 1e3:>12.2f}"
                   f"{(r['p50_s'] or 0) * 1e3:>10.2f}"
                   f"{(r['p95_s'] or 0) * 1e3:>10.2f}")
    return out


def _slowest_lines(traces: Sequence[Dict[str, Any]], n: int) -> List[str]:
    done = sorted(traces, key=lambda t: t.get("wall_s") or 0.0,
                  reverse=True)[:n]
    out = [f"{'trace':<34}{'uid':>6}{'wall(ms)':>11}  "
           f"{'flags / top segments'}"]
    for t in done:
        by_kind: Dict[str, float] = {}
        for s in t.get("spans") or []:
            k = str(s.get("kind", "?"))
            by_kind[k] = by_kind.get(k, 0.0) + float(s.get("dur_s", 0.0))
        top = sorted(by_kind.items(), key=lambda kv: kv[1], reverse=True)[:3]
        desc = " ".join(f"{k}={v * 1e3:.0f}ms" for k, v in top)
        flags = ",".join(t.get("flags") or [])
        out.append(f"{str(t['trace']):<34}{str(t.get('uid', '-')):>6}"
                   f"{(t.get('wall_s') or 0.0) * 1e3:>11.1f}  "
                   f"{('[' + flags + '] ') if flags else ''}{desc}")
    return out


def waterfall_lines(trace: Dict[str, Any], width: int = 32) -> List[str]:
    """ASCII span timeline for one request, spans ordered by start."""
    spans = sorted(trace.get("spans") or [],
                   key=lambda s: float(s.get("t0", 0.0)))
    out = []
    flags = ",".join(trace.get("flags") or [])
    wall = trace.get("wall_s")
    head = f"trace {trace['trace']} uid={trace.get('uid')}"
    if wall is not None:
        head += f" wall={wall * 1e3:.1f}ms"
    if flags:
        head += f" flags=[{flags}]"
    out.append(head)
    if not spans:
        out.append("  (no spans)")
        return out
    t_min = min(float(s["t0"]) for s in spans)
    t_max = max(float(s["t0"]) + float(s.get("dur_s", 0.0)) for s in spans)
    span_w = max(t_max - t_min, 1e-9)
    if wall:
        cov = span_coverage(spans, t_min, min(t_min + wall, t_max))
        out.append(f"  work-segment coverage: {cov * 100:.1f}% of the "
                   f"span window (route envelope excluded)")
    out.append(f"  {'t+ms':>9} {'segment':<16}{'component':<16}"
               f"{'dur(ms)':>10}  timeline")
    for s in spans:
        off = float(s["t0"]) - t_min
        dur = float(s.get("dur_s", 0.0))
        lo = int(off / span_w * width)
        hi = max(int((off + dur) / span_w * width), lo + 1)
        bar = " " * lo + "█" * min(hi - lo, width - lo)
        attrs = s.get("attrs") or {}
        tag = f" {attrs}" if attrs else ""
        out.append(f"  {off * 1e3:>9.1f} {str(s.get('kind', '?')):<16}"
                   f"{str(s.get('component', '?')):<16}{dur * 1e3:>10.2f}"
                   f"  |{bar:<{width}}|{tag}")
    return out


# --------------------------------------------------------------------- #
# Chrome export (reuses telemetry/trace.py's exporter)
# --------------------------------------------------------------------- #
def export_chrome(traces: Sequence[Dict[str, Any]], out_path: str) -> str:
    """Render the fleet-merged traces through the PR-2 span exporter:
    request spans become :class:`~..trace.SpanRecord`\\ s on a
    :class:`~..trace.Tracer` (components → tids), and
    ``Tracer.to_chrome_trace``/``export_chrome_trace`` do the rest."""
    from ..trace import SpanRecord, Tracer

    tracer = Tracer(enabled=True, jax_annotations=False,
                    max_spans=max(sum(len(t.get("spans") or [])
                                      for t in traces), 1))
    all_spans = [(t, s) for t in traces for s in t.get("spans") or []]
    if not all_spans:
        epoch = 0.0
    else:
        epoch = min(float(s.get("t0", 0.0)) for _, s in all_spans)
    tids: Dict[str, int] = {}
    for t, s in all_spans:
        comp = str(s.get("component", "?"))
        tid = tids.setdefault(comp, len(tids) + 1)
        attrs = dict(s.get("attrs") or {})
        attrs.update(trace=t["trace"], component=comp, uid=s.get("uid"))
        tracer._record(SpanRecord(
            name=str(s.get("kind", "?")),
            start_s=float(s.get("t0", 0.0)) - epoch,
            dur_s=float(s.get("dur_s", 0.0)),
            depth=0, parent=None, tid=tid, attrs=attrs, error=None))
    return tracer.export_chrome_trace(out_path)


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #
def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="dstpu-trace",
        description="Per-request span timelines from a request-trace "
                    "store: waterfalls, slowest-trace tables, segment "
                    "decomposition, Chrome-trace export.")
    p.add_argument("path", help="telemetry dir (containing traces.jsonl) "
                                "or a traces.jsonl path")
    p.add_argument("--request", default=None, metavar="TRACE_ID",
                   help="render one request's waterfall (unique id "
                        "prefix accepted)")
    p.add_argument("--slowest", type=int, default=None, metavar="N",
                   help="show only the N slowest traces")
    p.add_argument("--chrome", default=None, metavar="OUT_JSON",
                   help="export the fleet-merged view as a Chrome trace")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit JSON instead of text")
    args = p.parse_args(argv)

    src = args.path if not os.path.isdir(args.path) \
        else os.path.join(args.path, TRACES_FILE)
    from ..events import event_segments

    if not event_segments(src):
        print(f"dstpu-trace: no {TRACES_FILE}[.N] at {args.path}")
        return 2
    traces = load_traces(args.path)
    if not traces:
        print(f"dstpu-trace: no trace records in {src}")
        return 2

    if args.chrome:
        out = export_chrome(traces, args.chrome)
        print(f"dstpu-trace: wrote {len(traces)} trace(s) to {out}")
        return 0

    if args.request:
        trace = find_trace(traces, args.request)
        if trace is None:
            print(f"dstpu-trace: trace {args.request!r} not found "
                  f"(or the prefix is ambiguous) among {len(traces)} "
                  f"kept trace(s)")
            return 1
        if args.as_json:
            print(json.dumps(trace, indent=2, sort_keys=True, default=str))
        else:
            print("\n".join(waterfall_lines(trace)))
        return 0

    if args.slowest is not None:
        if args.as_json:
            done = sorted(traces, key=lambda t: t.get("wall_s") or 0.0,
                          reverse=True)[:args.slowest]
            print(json.dumps(done, indent=2, sort_keys=True, default=str))
        else:
            print("\n".join(_slowest_lines(traces, args.slowest)))
        return 0

    if args.as_json:
        print(json.dumps({"n_traces": len(traces),
                          "segments": segment_table(traces)},
                         indent=2, sort_keys=True, default=str))
        return 0
    flagged = sum(1 for t in traces if t.get("flags"))
    print(f"=== dstpu request traces ({src}) ===")
    print(f"kept traces: {len(traces)} ({flagged} flagged)")
    print("")
    print("--- per-segment decomposition (kept traces) ---")
    print("\n".join(segment_table_lines(segment_table(traces))))
    print("")
    print("--- slowest traces ---")
    print("\n".join(_slowest_lines(traces, 10)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
