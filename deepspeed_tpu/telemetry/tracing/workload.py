"""Recorded ``traces.jsonl`` → replayable workload, and the replay driver
behind ``bin/dstpu-replay``.

The tracing tier already records, per request, everything needed to
reconstruct the traffic that produced a telemetry run: the trace record's
``t_start`` gives the arrival time, ``prefill`` spans carry the prompt
chunk sizes (``resume`` chunks are preempt recompute, not client payload,
and are excluded), drained ``decode_window``/``verify``/``compile`` spans
carry the tokens produced, the router's ``route`` span carries the tenant
and stream flag, and ``draft``/``verify`` spans mark speculative decoding.
:func:`load_workload` folds a (possibly rotated) ``traces.jsonl`` into a
list of :class:`WorkloadRequest` with arrival *offsets*, so the same
traffic shape can be re-fired at any live ``dstpu-serve`` / ``dstpu-router``
endpoint — in real time or time-scaled — and the run scored from the
target's own goodput ledger (``/healthz`` → ``goodput`` section).

This is the substrate the autotuning loop needs: record once in
production, then replay the identical request mix against candidate
configs and compare ledger-scored verdicts instead of synthetic
benchmarks.

Replay fidelity contract: request *shape* (count, per-request prompt/output
lengths, tenants, arrival spacing) is reproduced exactly; prompt *content*
is synthetic (deterministic token ids of the recorded length — the trace
intentionally never records payload tokens).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from ..events import read_event_segments

#: span kinds whose ``tokens`` attr counts PROMPT tokens.  ``resume``-flagged
#: prefill chunks are preempt recompute of already-counted payload.
PROMPT_SPAN_KINDS = ("prefill",)

#: span kinds whose ``tokens`` attr counts produced OUTPUT tokens.  A
#: first-use window is retyped ``compile`` but its riders still produced
#: the recorded tokens, so compile spans count toward output length.
OUTPUT_SPAN_KINDS = ("decode_window", "verify", "compile")

#: presence of any of these spans marks the request as speculative
SPEC_SPAN_KINDS = ("draft", "verify")


# --------------------------------------------------------------------- #
# Workload model
# --------------------------------------------------------------------- #
@dataclass
class WorkloadRequest:
    """One recorded request, ready to re-fire."""

    trace_id: str
    arrival_s: float            # offset from the workload's first arrival
    prompt_tokens: int
    max_new_tokens: int
    tenant: str = "default"
    stream: bool = False
    speculative: bool = False
    shed: bool = False          # the RECORDED attempt was shed; replayed
    #                             anyway — offered load is the workload


@dataclass
class Workload:
    """An ordered (by arrival offset) replayable request list."""

    source: str
    requests: List[WorkloadRequest] = field(default_factory=list)

    @property
    def n_requests(self) -> int:
        return len(self.requests)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].arrival_s if self.requests else 0.0

    def tenants(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.requests:
            out[r.tenant] = out.get(r.tenant, 0) + 1
        return out

    def describe(self) -> Dict[str, Any]:
        reqs = self.requests
        return {
            "source": self.source,
            "n_requests": len(reqs),
            "duration_s": round(self.duration_s, 6),
            "tenants": self.tenants(),
            "shed_recorded": sum(1 for r in reqs if r.shed),
            "speculative": sum(1 for r in reqs if r.speculative),
            "stream": sum(1 for r in reqs if r.stream),
            "prompt_tokens_total": sum(r.prompt_tokens for r in reqs),
            "output_tokens_total": sum(r.max_new_tokens for r in reqs),
        }


def _span_tokens(spans: List[Dict[str, Any]], kinds) -> int:
    total = 0
    for sp in spans:
        if sp.get("kind") not in kinds:
            continue
        attrs = sp.get("attrs") or {}
        if sp.get("kind") in PROMPT_SPAN_KINDS and attrs.get("resume"):
            continue
        try:
            total += int(attrs.get("tokens") or 0)
        except (TypeError, ValueError):
            continue
    return total


def load_workload(path: str,
                  include_shed: bool = True,
                  default_prompt_tokens: int = 8,
                  default_max_new_tokens: int = 16) -> Workload:
    """Parse a (possibly rotated) ``traces.jsonl`` into a :class:`Workload`.

    A kept trace re-emits on every finish (router after replica on a
    shared store) — the newest line per trace id wins, exactly like the
    store's own loader.  Requests that were shed at record time carry
    ``shed=True`` and default prompt/output lengths (they never reached
    prefill, so the trace has no token counts for them); they are part of
    the *offered* load and replayed unless ``include_shed`` is False.
    """
    recs: Dict[str, Dict[str, Any]] = {}
    for row in read_event_segments(path):
        if row.get("kind") != "trace" or not row.get("trace"):
            continue
        recs[str(row["trace"])] = row        # later lines override
    out: List[WorkloadRequest] = []
    t_min: Optional[float] = None
    for rec in recs.values():
        try:
            t_start = float(rec["t_start"])
        except (KeyError, TypeError, ValueError):
            continue
        t_min = t_start if t_min is None else min(t_min, t_start)
    if t_min is None:
        return Workload(source=path, requests=[])
    for tid, rec in recs.items():
        try:
            t_start = float(rec["t_start"])
        except (KeyError, TypeError, ValueError):
            continue
        spans = rec.get("spans") or []
        flags = [str(f) for f in (rec.get("flags") or [])]
        tenant = "default"
        stream = False
        shed = any(str(f).startswith("shed") for f in flags)
        for sp in spans:
            attrs = sp.get("attrs") or {}
            if sp.get("kind") == "route":
                if attrs.get("tenant"):
                    tenant = str(attrs["tenant"])
                stream = bool(attrs.get("stream", False))
            elif sp.get("kind") == "admission":
                if attrs.get("shed"):
                    shed = True
                if attrs.get("tenant"):
                    tenant = str(attrs["tenant"])
        if shed and not include_shed:
            continue
        prompt = _span_tokens(spans, PROMPT_SPAN_KINDS)
        output = _span_tokens(spans, OUTPUT_SPAN_KINDS)
        if prompt:
            # the prefill's final forward seeds token 1 of the output;
            # the decode/verify windows carry only the remaining tokens
            output += 1
        out.append(WorkloadRequest(
            trace_id=tid,
            arrival_s=max(0.0, t_start - t_min),
            prompt_tokens=prompt or default_prompt_tokens,
            max_new_tokens=output or default_max_new_tokens,
            tenant=tenant,
            stream=stream,
            speculative=any(sp.get("kind") in SPEC_SPAN_KINDS
                            for sp in spans),
            shed=shed,
        ))
    out.sort(key=lambda r: (r.arrival_s, r.trace_id))
    return Workload(source=path, requests=out)


def synth_prompt(n_tokens: int, seed: int = 0) -> List[int]:
    """Deterministic synthetic token ids of the recorded length.  Small
    ids so any vocab the target model exposes covers them."""
    return [((seed * 131) + i * 17) % 47 + 1 for i in range(max(1,
                                                                n_tokens))]


# --------------------------------------------------------------------- #
# Replay driver
# --------------------------------------------------------------------- #
def _post_generate(url: str, body: Dict[str, Any],
                   timeout_s: float) -> Dict[str, Any]:
    """One blocking (or drained-SSE) request; returns outcome fields."""
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        f"{url}/v1/generate", data=data,
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            if body.get("stream"):
                # SSE: drain the event stream; tokens arrive as lines
                while r.readline():
                    pass
                payload: Dict[str, Any] = {}
            else:
                payload = json.loads(r.read() or b"{}")
            code = r.status
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except (ValueError, OSError):
            payload = {}
        code = e.code
    except Exception as e:  # noqa: BLE001 — transport failure is an outcome
        return {"code": 0, "error": repr(e),
                "wall_s": time.perf_counter() - t0}
    out = {"code": code, "wall_s": time.perf_counter() - t0}
    if isinstance(payload, dict):
        if payload.get("reason"):
            out["reason"] = payload["reason"]
        toks = payload.get("tokens")
        if isinstance(toks, list):
            out["tokens"] = len(toks)
    return out


def _fetch_goodput(url: str, timeout_s: float = 5.0) \
        -> Optional[Dict[str, Any]]:
    """The target's ledger view: ``/healthz`` ``goodput`` section (serve:
    own snapshot; router: fleet rollup), falling back to ``/goodput``."""
    for path, key in (("/healthz", "goodput"), ("/goodput", None)):
        try:
            with urllib.request.urlopen(f"{url}{path}",
                                        timeout=timeout_s) as r:
                body = json.loads(r.read())
        except Exception:  # noqa: BLE001 — scoring is best-effort
            continue
        gp = body.get(key) if key else body
        if isinstance(gp, dict) and "categories" in gp:
            return gp
    return None


def _percentile(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    vs = sorted(vals)
    idx = min(len(vs) - 1, max(0, int(round(q / 100.0 * (len(vs) - 1)))))
    return vs[idx]


def replay(workload: Workload, url: str,
           time_scale: float = 1.0,
           timeout_s: float = 60.0,
           tenant_override: Optional[str] = None,
           max_concurrency: int = 64) -> Dict[str, Any]:
    """Fire the workload at ``url`` honoring (scaled) arrival offsets and
    return a ledger-scored verdict.

    ``time_scale > 1`` compresses time (2.0 → twice as fast);
    arrival *order* and relative spacing shape are preserved either way.
    The verdict carries per-request outcomes, arrival-fidelity stats
    (scheduled-vs-actual fire lag), and — when the target has a goodput
    ledger installed — the post-run ledger snapshot plus its
    ``goodput_fraction`` as the scalar score.
    """
    url = url.rstrip("/")
    if "://" not in url:
        url = "http://" + url
    scale = max(time_scale, 1e-6)
    sem = threading.Semaphore(max(1, int(max_concurrency)))
    results: List[Optional[Dict[str, Any]]] = [None] * len(workload.requests)
    epoch = time.perf_counter()

    def _one(i: int, r: WorkloadRequest) -> None:
        scheduled = r.arrival_s / scale
        delay = epoch + scheduled - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        with sem:
            fired = time.perf_counter() - epoch
            body: Dict[str, Any] = {
                "prompt": synth_prompt(r.prompt_tokens, seed=i),
                "max_new_tokens": int(r.max_new_tokens),
                "tenant": tenant_override or r.tenant,
            }
            if r.stream:
                body["stream"] = True
            out = _post_generate(url, body, timeout_s=timeout_s)
        out.update(trace_id=r.trace_id, scheduled_s=round(scheduled, 6),
                   fired_s=round(fired, 6),
                   lag_s=round(fired - scheduled, 6))
        results[i] = out

    threads = [threading.Thread(target=_one, args=(i, r), daemon=True)
               for i, r in enumerate(workload.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - epoch

    done = [r for r in results if r is not None]
    ok = [r for r in done if 200 <= r.get("code", 0) < 300]
    shed = [r for r in done if r.get("code") in (429, 503)]
    errors = [r for r in done if r.get("code", 0) == 0
              or r.get("code", 0) >= 400 and r.get("code") not in (429,
                                                                   503)]
    lags = [r["lag_s"] for r in done if "lag_s" in r]
    goodput = _fetch_goodput(url)
    verdict: Dict[str, Any] = {
        "url": url,
        "source": workload.source,
        "time_scale": time_scale,
        "wall_s": round(wall, 6),
        "n_requests": len(workload.requests),
        "completed": len(ok),
        "shed": len(shed),
        "errors": len(errors),
        "arrival": {
            "max_lag_s": round(max(lags), 6) if lags else None,
            "p95_lag_s": round(_percentile(lags, 95), 6) if lags else None,
            "mean_lag_s": round(sum(lags) / len(lags), 6) if lags else None,
        },
        "goodput": goodput,
        "score": (goodput or {}).get("goodput_fraction"),
        "requests": done,
    }
    return verdict


# --------------------------------------------------------------------- #
# CLI (bin/dstpu-replay)
# --------------------------------------------------------------------- #
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu-replay",
        description="Replay a recorded traces.jsonl against a live "
                    "dstpu-serve / dstpu-router endpoint and score the "
                    "run from the target's goodput ledger.")
    p.add_argument("traces", help="traces.jsonl (rotated segments found "
                                  "automatically)")
    p.add_argument("--url", required=True,
                   help="target base URL, e.g. http://127.0.0.1:8100")
    p.add_argument("--time-scale", type=float, default=1.0,
                   help="arrival-time compression: 2.0 replays twice as "
                        "fast (default 1.0 = real time)")
    p.add_argument("--limit", type=int, default=None,
                   help="replay only the first N requests by arrival")
    p.add_argument("--skip-shed", action="store_true",
                   help="drop requests that were shed at record time "
                        "(default: replay the full offered load)")
    p.add_argument("--tenant", default=None,
                   help="override every request's tenant")
    p.add_argument("--timeout-s", type=float, default=60.0)
    p.add_argument("--describe", action="store_true",
                   help="print the parsed workload and exit (no traffic)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="write the full verdict JSON here "
                        "(default: stdout summary only)")
    args = p.parse_args(argv)

    wl = load_workload(args.traces, include_shed=not args.skip_shed)
    if args.limit is not None:
        wl = Workload(source=wl.source, requests=wl.requests[:args.limit])
    if args.describe:
        print(json.dumps({"workload": wl.describe(),
                          "requests": [asdict(r) for r in wl.requests]},
                         indent=2))
        return 0
    if not wl.requests:
        print(f"dstpu-replay: no replayable traces in {args.traces}",
              file=sys.stderr)
        return 1

    d = wl.describe()
    print(f"dstpu-replay: {d['n_requests']} requests over "
          f"{d['duration_s']:.2f}s recorded "
          f"(x{args.time_scale:g} replay) -> {args.url}")
    verdict = replay(wl, args.url, time_scale=args.time_scale,
                     timeout_s=args.timeout_s,
                     tenant_override=args.tenant)
    verdict["workload"] = d
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdict, f, indent=2)
    arr = verdict["arrival"]
    score = verdict["score"]
    print(f"dstpu-replay: completed {verdict['completed']}"
          f"/{verdict['n_requests']} "
          f"(shed {verdict['shed']}, errors {verdict['errors']}) "
          f"in {verdict['wall_s']:.2f}s; "
          f"arrival p95 lag "
          f"{arr['p95_lag_s'] if arr['p95_lag_s'] is not None else '?'}s")
    if score is not None:
        gp = verdict["goodput"]
        print(f"dstpu-replay: goodput score {score:.4f} "
              f"(compute fraction of {gp['wall_s']:.2f}s ledger wall; "
              f"conserved={gp.get('conserved')})")
    else:
        print("dstpu-replay: target has no goodput ledger "
              "(score unavailable)")
    return 0 if verdict["errors"] == 0 else 1


if __name__ == "__main__":                      # pragma: no cover
    sys.exit(main())
