"""Request-trace store: bounded ring + JSONL + tail-based sampling.

Every hop that owns a :class:`~.context.TraceContext` appends TYPED spans
here — ``queue_wait``, ``admission``, ``compile``, ``prefill``,
``kv_ship_{encode,wire,import}``, ``decode_window``, ``preempt``,
``resume``, ``reroute``, ``draft``, ``verify``, ``route`` — each carrying
the request uid, a wall-clock ``t0`` (unix seconds, so spans from
different processes merge onto one timeline) and a duration.  The store is
process-global (:func:`install_trace_store` / :func:`get_trace_store`),
mirroring the telemetry hub's install pattern: ``None`` IS the disabled
fast path, every instrumentation site guards with one global read.

Merging: a replica returns its spans IN-BAND with the HTTP response
(``trace`` field on ``/v1/generate`` / ``/v1/prefill`` bodies and terminal
SSE events); the router :meth:`merge`\\ s them into its own store, so
host-0/the router owns the fleet-merged view.  Spans dedupe by a per-span
``sid``, which makes merging idempotent — including the in-process fleet
harness where router and replicas share one global store.

Tail-based sampling (the keep/drop decision runs at trace COMPLETION,
when the interesting-ness is known):

  * always keep FLAGGED traces — shed / preempted / rerouted /
    nan_isolated / deadline_expired / drain_expired / mid_stream_error /
    window_hang;
  * always keep traces holding a TTFT/TPOT exemplar slot (the histogram
    tail must link to retrievable traces);
  * keep the slow cohort — wall time at or above the rolling p99 of
    recently finished traces (armed once enough walls are seen);
  * sample the steady-state remainder 1-in-``sample_every``.

Kept traces land in the bounded in-memory ring (the ``/traces`` live
endpoint and ``dstpu-trace``'s live views) and are written through to
``traces.jsonl`` (rotation-capable EventLog, ``kind: "trace"`` lines) for
the offline CLI; dropped traces are discarded wholesale, so steady-state
overhead stays bounded no matter the request rate.  Per-segment duration
aggregates (and the ``serving/trace_segment_s`` registry histogram behind
the ``dstpu-telemetry`` TTFT-decomposition section) are updated for EVERY
span, sampled out or not — the percentiles describe all traffic, the ring
holds the interesting subset.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import get_telemetry

#: canonical span taxonomy (attrs may refine; kinds stay closed so the
#: decomposition tables and the waterfall renderer have a stable axis)
SPAN_KINDS = (
    "queue_wait",      # submit → admission (per admission; resets on preempt)
    "admission",       # reservation + prefix/KV graft work at the queue head
    "compile",         # a first-use decode/verify window (wall = XLA compile)
    "prefill",         # one put() forward covering this request's chunk
    "kv_ship_encode",  # disagg producer: KV pages → canonical rows
    "kv_ship_wire",    # router-measured ship leg (HTTP minus replica time)
    "kv_ship_import",  # disagg consumer: rows → local page geometry
    "decode_window",   # one drained fused decode window
    "preempt",         # KV-pressure eviction marker
    "resume",          # preempted request back to DECODE after recompute
    "reroute",         # router moved zero-token work off a dead replica
    "draft",           # speculative drafter host time for one verify window
    "verify",          # one speculative verify window
    "route",           # router wrapper: admission → final forwarded byte
)

#: segment kinds whose p95s sum into the TTFT estimate (time queued plus
#: prompt service — the part of TTFT fleet capacity actually controls);
#: canonical here, the fleet controller imports it
TTFT_SEGMENTS = ("queue_wait", "prefill")

#: flags that force tail-sampling to KEEP a trace.  ``exemplar`` is set
#: by :meth:`RequestTraceStore.note_exemplar` itself: a flag rides the
#: in-band payload, so the ROUTER's independently-sampled merged copy is
#: kept too and the histogram-tail link resolves fleet-wide (a slot later
#: stolen by a larger value leaves the flag — a small over-keep bias on
#: exactly the traces worth keeping)
ALWAYS_KEEP_FLAGS = ("shed", "preempted", "rerouted", "nan_isolated",
                     "deadline_expired", "drain_expired",
                     "mid_stream_error", "window_hang",
                     "prefill_fallback", "exemplar")

#: retirement reason → trace flag (satellite: incidents name the victim)
FLAG_BY_REASON = {
    "nan": "nan_isolated",
    "deadline": "deadline_expired",
    "ttft_timeout": "deadline_expired",
    "drain_deadline": "drain_expired",
    "queue_full": "shed",
    "draining": "shed",
}


# span ids: a per-process random prefix + a counter — unique across the
# fleet for merge dedupe, ~10x cheaper than a uuid4 per span (spans are
# recorded inside the decode window hot path)
_SID_PREFIX = os.urandom(4).hex()
_SID_COUNTER = itertools.count()


def _sid() -> str:
    return f"{_SID_PREFIX}{next(_SID_COUNTER):x}"


class RequestTraceStore:
    """One process's view of request traces (see module docstring)."""

    def __init__(self, jsonl_path: Optional[str] = None,
                 max_traces: int = 256, max_spans_per_trace: int = 512,
                 sample_every: int = 10, slow_quantile: float = 0.99,
                 slow_min_samples: int = 32, wall_window: int = 512,
                 exemplar_k: int = 4, segment_window: int = 512,
                 segment_window_s: float = 60.0,
                 jsonl_max_mb: float = 64.0, clock=time.monotonic):
        self.sample_every = max(int(sample_every), 1)
        self.slow_quantile = float(slow_quantile)
        self.slow_min_samples = int(slow_min_samples)
        self.max_traces = max(int(max_traces), 1)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.exemplar_k = int(exemplar_k)
        self._lock = threading.RLock()
        #: trace_id → record; records carry done/kept marks and stay in
        #: this one ordered map so late spans (amend semantics) and
        #: re-finishes (router after replica, in-process) just work
        self._traces: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        #: sid tombstones of traces evicted while still ACTIVE (> max
        #: concurrent in-flight): a late span/merge for such a trace must
        #: neither re-count trace/started nor re-observe merged segments
        self._evicted_seen: "collections.OrderedDict[str, set]" = \
            collections.OrderedDict()
        self._walls: "collections.deque[float]" = collections.deque(
            maxlen=int(wall_window))
        self._segments: Dict[str, "collections.deque[float]"] = {}
        self._segment_window = int(segment_window)
        #: TIME-windowed (ts, dur) pairs per kind: the count-bounded deque
        #: above keeps stale breaches alive forever under low traffic, so
        #: the rolling p95 the fleet controller trusts (``p95_window_s``)
        #: only sees the last ``segment_window_s`` seconds
        self._seg_recent: Dict[str,
                               "collections.deque[Tuple[float, float]]"] = {}
        self.segment_window_s = float(segment_window_s)
        self.clock = clock
        self._seg_totals: Dict[str, Tuple[int, float]] = {}
        self._exemplars: Dict[str, List[Tuple[float, str]]] = {}
        self._finish_seq = 0
        self.counters: "collections.Counter[str]" = collections.Counter()
        self._log = None
        if jsonl_path:
            from ..events import EventLog

            self._log = EventLog(
                jsonl_path, max_bytes=int(jsonl_max_mb * 1024 * 1024))

    # ---------------------------------------------------------------- #
    # Recording
    # ---------------------------------------------------------------- #
    def _record(self, trace_id: str) -> Dict[str, Any]:
        rec = self._traces.get(trace_id)
        if rec is None:
            was_evicted = self._evicted_seen.pop(trace_id, None)
            rec = self._traces[trace_id] = {
                "trace": trace_id, "uid": None, "t_start": time.time(),
                "spans": [], "flags": [], "wall_s": None,
                "done": False, "kept": False,
                # every sid ever appended — survives a sampling drop as
                # a tombstone so a later merge() (in-process shared
                # store) cannot re-observe the same spans
                "_seen": was_evicted if was_evicted is not None else set(),
            }
            if was_evicted is None:
                self.counters["trace/started"] += 1
                self._count_registry("trace/started")
            self._evict_locked()
        return rec

    def _evict_locked(self) -> None:
        while len(self._traces) > self.max_traces:
            # oldest DONE trace first; else the oldest of anything (an
            # abandoned active trace must not pin the ring forever)
            victim = next((t for t, r in self._traces.items() if r["done"]),
                          next(iter(self._traces)))
            rec = self._traces.pop(victim)
            if not rec["done"]:
                # still in flight (> max_traces concurrent): stash the
                # sid tombstones so a late span/merge neither double-
                # counts trace/started nor re-observes segments
                self._evicted_seen[victim] = rec["_seen"]
                while len(self._evicted_seen) > self.max_traces:
                    self._evicted_seen.popitem(last=False)
            self.counters["trace/evicted"] += 1

    def add_span(self, trace_id: str, kind: str, t0: float, dur_s: float,
                 component: str = "serve", uid: Optional[int] = None,
                 **attrs) -> Optional[Dict[str, Any]]:
        span = {"sid": _sid(), "kind": str(kind), "component": str(component),
                "uid": uid, "t0": float(t0), "dur_s": float(dur_s)}
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            rec = self._record(trace_id)
            if uid is not None:
                rec["uid"] = uid
            if len(rec["spans"]) >= self.max_spans_per_trace:
                self.counters["trace/spans_dropped"] += 1
                return None
            rec["spans"].append(span)
            rec["_seen"].add(span["sid"])
            self._observe_segment_locked(kind, dur_s)
        return span

    def merge(self, trace_id: str, payload: Optional[Dict[str, Any]]) -> int:
        """Fold a remote hop's trace payload (``{"trace", "spans",
        "flags", ...}`` — a :meth:`finish` return or response field) into
        this store.  Spans dedupe by ``sid``; segment aggregates only
        count genuinely-new spans, so the in-process fleet harness (one
        shared store) never double-counts.  Returns spans added."""
        if not payload or not isinstance(payload, dict):
            return 0
        spans = payload.get("spans") or []
        added = 0
        with self._lock:
            rec = self._record(trace_id)
            seen = rec["_seen"]
            # dedupe STORAGE against what the record currently holds, and
            # AGGREGATES against every sid ever observed: a span whose
            # sid is tombstoned but no longer stored (its first finish
            # sampled the trace out before this hop flagged it worth
            # keeping) is restored to the record without re-counting its
            # segment into the histograms
            stored = {s.get("sid") for s in rec["spans"]}
            for span in spans:
                if not isinstance(span, dict):
                    continue
                sid = span.get("sid") or _sid()
                if sid in stored:
                    continue
                if len(rec["spans"]) >= self.max_spans_per_trace:
                    self.counters["trace/spans_dropped"] += 1
                    break
                observe = sid not in seen
                span = dict(span)
                span["sid"] = sid
                rec["spans"].append(span)
                stored.add(sid)
                seen.add(sid)
                added += 1
                if observe:
                    try:
                        self._observe_segment_locked(
                            str(span.get("kind", "?")),
                            float(span.get("dur_s", 0.0)))
                    except (TypeError, ValueError):
                        pass
                if rec["uid"] is None and span.get("uid") is not None:
                    rec["uid"] = span["uid"]
            for fl in payload.get("flags") or []:
                if fl not in rec["flags"]:
                    rec["flags"].append(str(fl))
        return added

    def flag(self, trace_id: str, reason: str) -> None:
        with self._lock:
            rec = self._record(trace_id)
            if reason not in rec["flags"]:
                rec["flags"].append(str(reason))

    # ---------------------------------------------------------------- #
    # Exemplars (histogram tail → trace id links)
    # ---------------------------------------------------------------- #
    def note_exemplar(self, metric: str, value: float,
                      trace_id: str) -> bool:
        """Offer ``(value, trace_id)`` as a tail exemplar for ``metric``
        (``ttft_s`` / ``tpot_s``).  The top-``exemplar_k`` largest values
        win; a trace holding a slot is force-kept at finish so the link
        always resolves.  Returns True when the offer entered the set."""
        value = float(value)
        with self._lock:
            ex = self._exemplars.setdefault(metric, [])
            if any(t == trace_id for _, t in ex):
                return False
            if len(ex) >= self.exemplar_k and value <= min(ex)[0]:
                return False
            ex.append((value, trace_id))
            ex.sort(reverse=True)
            del ex[self.exemplar_k:]
            # the keep decision must travel with the trace (see
            # ALWAYS_KEEP_FLAGS): flag under the same lock hold
            rec = self._record(trace_id)
            if "exemplar" not in rec["flags"]:
                rec["flags"].append("exemplar")
        tel = get_telemetry()
        if tel is not None:
            tel.event("trace_exemplar", metric=metric,
                      value=round(value, 6), trace=trace_id)
        return True

    def exemplars(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return {m: [{"value": v, "trace": t} for v, t in ex]
                    for m, ex in self._exemplars.items()}

    def _is_exemplar_locked(self, trace_id: str) -> bool:
        return any(t == trace_id
                   for ex in self._exemplars.values() for _, t in ex)

    # ---------------------------------------------------------------- #
    # Completion + tail sampling
    # ---------------------------------------------------------------- #
    def _slow_threshold_locked(self) -> Optional[float]:
        if len(self._walls) < self.slow_min_samples:
            return None
        svals = sorted(self._walls)
        from ..metrics import _percentile

        return _percentile(svals, self.slow_quantile * 100.0)

    def finish(self, trace_id: str, flag: Optional[str] = None,
               wall_s: Optional[float] = None) -> Dict[str, Any]:
        """Seal a trace and run the tail-sampling keep/drop decision;
        returns the FULL record either way (in-band propagation to the
        next hop is never subject to local sampling).  Re-finishing an
        already-done trace (the router finishes after the replica did, on
        a shared in-process store) updates flags/wall and re-evaluates
        keep — a drop can upgrade to keep, never the reverse."""
        with self._lock:
            rec = self._record(trace_id)
            if flag and flag not in rec["flags"]:
                rec["flags"].append(str(flag))
            if wall_s is not None:
                rec["wall_s"] = float(wall_s)
            elif rec["wall_s"] is None:
                rec["wall_s"] = max(time.time() - rec["t_start"], 0.0)
            first_finish = not rec["done"]
            rec["done"] = True
            if first_finish:
                self._finish_seq += 1
                self.counters["trace/finished"] += 1
                self._count_registry("trace/finished")
                self._walls.append(rec["wall_s"])
            keep = bool(rec["flags"]) \
                or self._is_exemplar_locked(trace_id)
            if not keep and first_finish:
                # probabilistic keeps are decided ONCE, at the first
                # finish: a re-finish (router after replica on a shared
                # store) may only upgrade for DETERMINISTIC reasons
                # (flags/exemplar) — re-rolling the sampling counter
                # against a trace whose spans were already discarded
                # would keep nondeterministic, span-less records.
                # STRICTLY above the rolling p99: under perfectly uniform
                # walls nothing qualifies as "slow", so steady state
                # still samples 1-in-N instead of keeping everything
                thresh = self._slow_threshold_locked()
                keep = (thresh is not None and rec["wall_s"] > thresh) \
                    or (self._finish_seq - 1) % self.sample_every == 0
            newly_kept = keep and not rec["kept"]
            rec["kept"] = rec["kept"] or keep
            if rec["flags"] and not rec.get("_flag_counted"):
                rec["_flag_counted"] = True
                self.counters["trace/flagged"] += 1
                self._count_registry("trace/flagged")
            if first_finish:
                self.counters["trace/kept" if keep else "trace/dropped"] += 1
                self._count_registry(
                    "trace/kept" if keep else "trace/dropped")
            elif newly_kept:
                # drop→keep upgrade on a re-finish (a flag arrived after
                # the first finish, e.g. the router flagging a replica-
                # finished trace on a shared store): MOVE the snapshot
                # count so kept+dropped keeps agreeing with the ring/
                # jsonl, but keep the EXPORTED registry counters
                # monotonic (a scraper rate()s them; a decrement reads
                # as a counter reset) — upgrades get their own counter,
                # so scraped dropped-minus-upgraded matches the ring
                self.counters["trace/dropped"] -= 1
                self.counters["trace/kept"] += 1
                self.counters["trace/upgraded"] += 1
                self._count_registry("trace/kept")
                self._count_registry("trace/upgraded")
            if not rec["kept"]:
                # discard the span payload, keep a sid tombstone: a later
                # merge() of the same spans (in-process shared store, or
                # a retried in-band payload) must dedupe, not re-observe
                # the segment aggregates.  The tombstone is a few sids,
                # ring-bounded like everything else.
                out = dict(rec, spans=list(rec["spans"]),
                           flags=list(rec["flags"]))
                for k in ("_seen", "_flag_counted"):
                    out.pop(k, None)
                rec["spans"] = []
                return out
            if self._log is not None:
                # every finish of a kept trace re-emits: a re-finish
                # (router after replica on a shared store) carries spans
                # and the true end-to-end wall the first emit predates —
                # the loader takes the newest line per trace id
                self._log.emit("trace",
                               **{k: v for k, v in rec.items()
                                  if k not in ("done", "kept", "_seen",
                                               "_flag_counted")})
            return rec

    # ---------------------------------------------------------------- #
    # Reads (live /traces endpoint, dstpu-trace, tests)
    # ---------------------------------------------------------------- #
    @staticmethod
    def _copy(rec: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rec, spans=list(rec["spans"]), flags=list(rec["flags"]))
        for k in ("_seen", "_flag_counted"):
            out.pop(k, None)
        return out

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None or (rec["done"] and not rec["kept"]):
                return None                    # unknown or sampled out
            return self._copy(rec)

    def traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._copy(r) for r in self._traces.values()
                    if not (r["done"] and not r["kept"])]

    def slowest(self, n: int = 10) -> List[Dict[str, Any]]:
        done = [r for r in self.traces() if r["done"]]
        done.sort(key=lambda r: r.get("wall_s") or 0.0, reverse=True)
        return done[:max(int(n), 0)]

    def segment_summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-segment duration stats over EVERY observed span (kept and
        sampled-out alike): count/total plus p50/p95 from the bounded
        recent window — the live TTFT/TPOT decomposition."""
        from ..metrics import _percentile

        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            now = self.clock()
            for kind, window in self._segments.items():
                count, total = self._seg_totals.get(kind, (0, 0.0))
                svals = sorted(window)
                recent = self._seg_recent.get(kind)
                rvals = []
                if recent is not None:
                    self._expire_recent_locked(recent, now)
                    rvals = sorted(d for _, d in recent)
                out[kind] = {
                    "count": count, "total_s": total,
                    "mean_s": total / count if count else 0.0,
                    "p50_s": _percentile(svals, 50) if svals else None,
                    "p95_s": _percentile(svals, 95) if svals else None,
                    # rolling TIME window (last segment_window_s seconds):
                    # None once traffic goes quiet — a stale breach must
                    # age out of the controller's overload signal
                    "p95_window_s": _percentile(rvals, 95) if rvals
                    else None,
                }
        return out

    def ttft_p95_window_s(self) -> Optional[float]:
        """Rolling-window TTFT p95 estimate: the sum of the time-windowed
        segment p95s over the TTFT segments (queue_wait + prefill); None
        when the window holds no recent traffic."""
        summary = self.segment_summary()
        parts = [row.get("p95_window_s") for kind, row in summary.items()
                 if kind in TTFT_SEGMENTS
                 and row.get("p95_window_s") is not None]
        return float(sum(parts)) if parts else None

    def _expire_recent_locked(self, recent, now: float) -> None:
        horizon = now - self.segment_window_s
        while recent and recent[0][0] < horizon:
            recent.popleft()

    def _observe_segment_locked(self, kind: str, dur_s: float) -> None:
        win = self._segments.get(kind)
        if win is None:
            win = self._segments[kind] = collections.deque(
                maxlen=self._segment_window)
        win.append(dur_s)
        recent = self._seg_recent.get(kind)
        if recent is None:
            recent = self._seg_recent[kind] = collections.deque(
                maxlen=self._segment_window)
        now = self.clock()
        recent.append((now, dur_s))
        self._expire_recent_locked(recent, now)
        count, total = self._seg_totals.get(kind, (0, 0.0))
        self._seg_totals[kind] = (count + 1, total + dur_s)
        tel = get_telemetry()
        if tel is not None:
            tel.metrics.histogram("serving/trace_segment_s").observe(
                dur_s, segment=kind)

    def _count_registry(self, name: str) -> None:
        tel = get_telemetry()
        if tel is not None:
            tel.metrics.counter(name).inc()

    def flush(self) -> None:
        if self._log is not None:
            self._log.flush()

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


# --------------------------------------------------------------------- #
# Process-global instance (telemetry-hub install pattern)
# --------------------------------------------------------------------- #
_GLOBAL: Optional[RequestTraceStore] = None
_GLOBAL_LOCK = threading.Lock()


def install_trace_store(store: Optional[RequestTraceStore]
                        ) -> Optional[RequestTraceStore]:
    """Install (or clear, with None) the process-global trace store."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        previous, _GLOBAL = _GLOBAL, store
    return previous


def get_trace_store() -> Optional[RequestTraceStore]:
    return _GLOBAL


def add_trace_cli_args(parser) -> None:
    """The tracing flags shared by ``dstpu-serve`` and ``dstpu-router``."""
    parser.add_argument("--no-trace", action="store_true",
                        help="disable request tracing (spans, /traces, "
                             "traces.jsonl)")
    parser.add_argument("--trace-sample", type=int, default=10,
                        help="tail-sampling rate: keep 1-in-N steady-state "
                             "traces (flagged/slow/exemplar traces are "
                             "always kept); 1 keeps everything")


def install_trace_store_from_cli(args,
                                 telemetry_dir: str
                                 ) -> Optional[RequestTraceStore]:
    """Build + install the process store from :func:`add_trace_cli_args`
    flags; ``--no-trace`` installs nothing (the disabled fast path)."""
    if getattr(args, "no_trace", False):
        return None
    store = RequestTraceStore(
        jsonl_path=os.path.join(telemetry_dir, "traces.jsonl"),
        sample_every=args.trace_sample)
    install_trace_store(store)
    return store


# --------------------------------------------------------------------- #
# Shared recording helpers: the store-None/trace-None disabled fast path
# every recorder (LifecycleScheduler, FleetRouter, servers) needs.  One
# copy here so a change to the disabled-path contract happens once.
# --------------------------------------------------------------------- #
def trace_id_of(trace) -> Optional[str]:
    """The trace id for event/log payloads, or None when untraced."""
    return trace.trace_id if trace is not None else None


def record_span(trace, kind: str, t0: float, dur_s: float,
                component: str, **attrs) -> None:
    """Append a typed span for ``trace`` to the installed store; no-op
    when tracing is disabled or the request is untraced."""
    store = get_trace_store()
    if store is None or trace is None:
        return
    store.add_span(trace.trace_id, kind, t0=t0, dur_s=dur_s,
                   component=component, **attrs)


def merge_trace(trace, body) -> None:
    """Merge an in-band span payload (``body["trace"]``) from a
    downstream hop's response into ``trace``; no-op when disabled,
    untraced, or the body carries no payload."""
    store = get_trace_store()
    if store is None or trace is None or not isinstance(body, dict):
        return
    store.merge(trace.trace_id, body.get("trace"))


def flag_trace(trace, reason: str) -> None:
    """Attach an always-keep flag to ``trace``; no-op when disabled or
    untraced."""
    store = get_trace_store()
    if store is not None and trace is not None:
        store.flag(trace.trace_id, reason)


# --------------------------------------------------------------------- #
# Shared helpers (coverage math + /traces endpoint payload)
# --------------------------------------------------------------------- #
def span_coverage(spans: Sequence[Dict[str, Any]], t0: float, t1: float,
                  exclude: Tuple[str, ...] = ("route",)) -> float:
    """Fraction of ``[t0, t1]`` covered by the UNION of span intervals.
    Wrapper spans (``route`` — the router leg that by construction covers
    nearly the whole request) are excluded by default so the number
    reflects attributed WORK segments, not envelopes."""
    if t1 <= t0:
        return 0.0
    ivals = []
    for s in spans:
        if s.get("kind") in exclude:
            continue
        a = max(float(s.get("t0", 0.0)), t0)
        b = min(float(s.get("t0", 0.0)) + float(s.get("dur_s", 0.0)), t1)
        if b > a:
            ivals.append((a, b))
    ivals.sort()
    covered = 0.0
    cur_a = cur_b = None
    for a, b in ivals:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    return covered / (t1 - t0)


def traces_endpoint_payload(query: Dict[str, Any]
                            ) -> Tuple[int, Dict[str, Any]]:
    """The ``GET /traces`` body shared by dstpu-serve, dstpu-router and
    the live observability server.  ``query`` is a parse_qs dict:
    ``?request=<trace_id>`` → one full trace (404 when unknown/sampled
    out); ``?slowest=N`` → the N slowest; default → summary (segment
    decomposition, counters, exemplars, slowest few)."""
    store = get_trace_store()
    if store is None:
        return 404, {"error": "request tracing disabled "
                              "(no trace store installed)"}

    def _q(name):
        v = query.get(name)
        return v[0] if isinstance(v, (list, tuple)) and v else v

    want = _q("request") or _q("trace")
    if want:
        rec = store.get(str(want))
        if rec is None:
            return 404, {"error": f"unknown trace {want} "
                                  f"(never seen, evicted, or sampled out)"}
        rec.pop("done", None)
        rec.pop("kept", None)
        return 200, rec
    try:
        n = int(_q("slowest") or 5)
    except (TypeError, ValueError):
        n = 5
    slow = []
    for rec in store.slowest(n):
        by_kind: Dict[str, float] = {}
        for s in rec["spans"]:
            # merge() stores in-band spans verbatim — a version-skewed
            # replica's span may lack keys; the live endpoint must not
            # 500 on it
            kind = str(s.get("kind", "?"))
            try:
                dur = float(s.get("dur_s") or 0.0)
            except (TypeError, ValueError):
                dur = 0.0
            by_kind[kind] = by_kind.get(kind, 0.0) + dur
        slow.append({"trace": rec["trace"], "uid": rec["uid"],
                     "wall_s": rec["wall_s"], "flags": rec["flags"],
                     "n_spans": len(rec["spans"]),
                     "segments_s": {k: round(v, 6)
                                    for k, v in sorted(by_kind.items())}})
    return 200, {
        "segments": store.segment_summary(),
        "ttft_p95_window_s": store.ttft_p95_window_s(),
        "ttft_window_s": store.segment_window_s,
        "counters": dict(store.counters),
        "exemplars": store.exemplars(),
        "slowest": slow,
    }
