"""Unified telemetry: structured tracing, metrics, events, memory sampling.

Enable via the ``telemetry`` config block (see ``runtime/config.py``):

    {"telemetry": {"enabled": true, "output_dir": "telemetry_out"}}

then summarize a finished run with ``bin/dstpu-telemetry <output_dir>``,
compare it against bench history with ``dstpu-telemetry <run> --compare``,
or watch it live via the ``telemetry.live`` HTTP plane
(``deepspeed_tpu/telemetry/live/``).
"""
from .events import EventLog, read_event_segments, read_jsonl
from .goodput import (GOODPUT_CATEGORIES, GoodputLedger, get_goodput_ledger,
                      goodput_residual, install_goodput_ledger,
                      record_goodput, rollup_goodput)
from .hub import (Telemetry, emit_event, get_telemetry, set_telemetry, span,
                  telemetry_enabled)
from .memory import (MEM_BUCKETS, MemoryLedger, MemorySampler,
                     get_memory_ledger, install_memory_ledger, rollup_memory)
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .trace import NULL_SPAN, SpanRecord, Tracer

__all__ = [
    "Counter", "EventLog", "GOODPUT_CATEGORIES", "Gauge", "GoodputLedger",
    "Histogram", "MEM_BUCKETS", "MemoryLedger", "MemorySampler",
    "MetricsRegistry", "NULL_SPAN", "SpanRecord", "Telemetry", "Tracer",
    "emit_event", "get_goodput_ledger", "get_memory_ledger", "get_telemetry",
    "goodput_residual", "install_goodput_ledger", "install_memory_ledger",
    "read_event_segments", "read_jsonl",
    "record_goodput", "rollup_goodput", "rollup_memory", "set_telemetry",
    "span", "telemetry_enabled",
]
