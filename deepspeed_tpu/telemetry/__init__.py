"""Unified telemetry: structured tracing, metrics, events, memory sampling.

Enable via the ``telemetry`` config block (see ``runtime/config.py``):

    {"telemetry": {"enabled": true, "output_dir": "telemetry_out"}}

then summarize a finished run with ``bin/dstpu-telemetry <output_dir>``,
compare it against bench history with ``dstpu-telemetry <run> --compare``,
or watch it live via the ``telemetry.live`` HTTP plane
(``deepspeed_tpu/telemetry/live/``).
"""
from .events import EventLog, read_event_segments, read_jsonl
from .hub import (Telemetry, emit_event, get_telemetry, set_telemetry, span,
                  telemetry_enabled)
from .memory import MemorySampler
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .trace import NULL_SPAN, SpanRecord, Tracer

__all__ = [
    "Counter", "EventLog", "Gauge", "Histogram", "MemorySampler",
    "MetricsRegistry", "NULL_SPAN", "SpanRecord", "Telemetry", "Tracer",
    "emit_event", "get_telemetry", "read_event_segments", "read_jsonl",
    "set_telemetry", "span", "telemetry_enabled",
]
