"""Cross-run performance regression tracking against BENCH history.

``bench.py`` leaves one ``BENCH_r*.json`` per run at the repo root — a
step-time / MFU / tokens-per-chip record of every prior session.  This
module turns that archive into a regression gate: extract the comparable
metrics from the current run (a bench JSON *or* a telemetry output dir),
take the median of the history as the baseline (median, not mean — one
broken historical run must not move the bar), and flag any metric that
moved past ``threshold`` in its *bad* direction.  Step time and exposed
comm regress upward; MFU and throughput regress downward.

Consumed by ``dstpu-telemetry --compare`` (exit code 3 on a regression so
CI can gate without parsing output) and by ``tools/check_telemetry_cli.py``.
"""
from __future__ import annotations

import glob
import json
import math
import os
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

DEFAULT_PATTERN = "BENCH_r*.json"

#: metric key → (direction, description); direction +1 = higher is better
METRICS: Dict[str, Tuple[int, str]] = {
    "step_time_s": (-1, "mean optimizer-step wall time"),
    "mfu": (+1, "model flops utilization"),
    "tokens_per_sec_per_chip": (+1, "training throughput per chip"),
    "exposed_comm_fraction": (-1, "device time exposed on communication"),
}

VERDICT_REGRESSION = "regression"
VERDICT_OK = "ok"
VERDICT_NO_HISTORY = "no-history"


# ------------------------------------------------------------------- #
# Extraction
# ------------------------------------------------------------------- #
def extract_bench_metrics(doc: Dict[str, Any]) -> Dict[str, float]:
    """Comparable metrics from one BENCH_r*.json (or a bare bench ``parsed``
    payload).  Runs that never produced numbers (``parsed: null`` — e.g. no
    accelerator that day) extract to {} and are skipped upstream."""
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else (
        doc if "metric" in doc else None)
    if not parsed:
        return {}
    out: Dict[str, float] = {}
    extra = parsed.get("extra") or {}
    if isinstance(extra.get("step_time_s"), (int, float)):
        out["step_time_s"] = float(extra["step_time_s"])
    if isinstance(extra.get("mfu"), (int, float)):
        out["mfu"] = float(extra["mfu"])
    if isinstance(extra.get("exposed_comm_fraction"), (int, float)):
        out["exposed_comm_fraction"] = float(extra["exposed_comm_fraction"])
    unit = str(parsed.get("unit", ""))
    if isinstance(parsed.get("value"), (int, float)) and \
            unit.startswith("tokens/s"):
        out["tokens_per_sec_per_chip"] = float(parsed["value"])
    return out


def extract_run_metrics(summary: Dict[str, Any]) -> Dict[str, float]:
    """Comparable metrics from a ``summarize_run`` digest (a telemetry
    output dir): step time from the ``engine/train_batch`` span row, MFU
    from the roofline gauges, exposed comm from the overlap gauges."""
    out: Dict[str, float] = {}
    for row in summary.get("step_breakdown") or []:
        if row.get("phase") == "engine/train_batch" and row.get("count"):
            out["step_time_s"] = float(row["mean_s"])
            break
    prof = summary.get("profile") or {}
    roof = (prof.get("report") or {}).get("roofline") or \
        prof.get("roofline_gauges") or {}
    if isinstance(roof.get("mfu"), (int, float)):
        out["mfu"] = float(roof["mfu"])
    ov = summary.get("overlap") or {}
    if isinstance(ov.get("exposed_comm_fraction"), (int, float)):
        out["exposed_comm_fraction"] = float(ov["exposed_comm_fraction"])
    return out


def load_history(history_dir: str, pattern: str = DEFAULT_PATTERN,
                 exclude: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every readable history entry, sorted by filename (run order):
    ``[{"file", "metrics"}, ...]``; entries with no numbers keep ``metrics:
    {}`` so callers can report how much history was unusable.  ``exclude``
    drops one path — the run UNDER comparison often sits in the same dir
    (bench.py writes to the repo root), and letting it join its own
    baseline dilutes the median toward itself, masking the regression."""
    entries: List[Dict[str, Any]] = []
    skip = os.path.abspath(exclude) if exclude else None
    for path in sorted(glob.glob(os.path.join(history_dir, pattern))):
        if skip and os.path.abspath(path) == skip:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            entries.append({"file": path, "metrics": {}, "unreadable": True})
            continue
        entries.append({"file": path, "metrics": extract_bench_metrics(doc)})
    return entries


def current_metrics_from_path(path: str) -> Dict[str, float]:
    """The current run's metrics from either source: a bench JSON file, or
    a telemetry output dir (events.jsonl summarized on the spot)."""
    if os.path.isfile(path) and path.endswith(".json"):
        with open(path) as f:
            return extract_bench_metrics(json.load(f))
    from .summary import summarize_run

    events_path = os.path.join(path, "events.jsonl") \
        if os.path.isdir(path) else path
    trace_path = os.path.join(path, "trace.json") \
        if os.path.isdir(path) else None
    return extract_run_metrics(summarize_run(events_path, trace_path))


# ------------------------------------------------------------------- #
# Comparison
# ------------------------------------------------------------------- #
def compare_runs(current: Dict[str, float],
                 history: Sequence[Dict[str, Any]],
                 threshold: float = 0.15,
                 min_history: int = 1) -> Dict[str, Any]:
    """Verdict over every metric present in both the current run and at
    least ``min_history`` usable history entries.  ``delta`` is signed so a
    +0.30 on ``step_time_s`` reads as "30% slower"."""
    usable = [h for h in history if h.get("metrics")]
    rows: Dict[str, Dict[str, Any]] = {}
    regressions: List[str] = []
    for name, (direction, desc) in METRICS.items():
        if name not in current:
            continue
        past = [h["metrics"][name] for h in usable if name in h["metrics"]]
        if len(past) < min_history:
            continue
        baseline = statistics.median(past)
        cur = float(current[name])
        if baseline:
            delta = (cur - baseline) / abs(baseline)
        else:
            # a zero baseline (e.g. exposed_comm_fraction fully overlapped
            # in every prior run) must still flag ANY move off it — delta 0
            # here would make the one regression this metric can have
            # structurally invisible to the gate
            delta = math.inf if cur > 0 else 0.0
        # positive worsening: how far the metric moved in its bad direction
        worsening = -delta if direction > 0 else delta
        regressed = worsening > threshold
        rows[name] = {
            "current": cur,
            "baseline": baseline,
            "n_history": len(past),
            # an infinite delta (off a zero baseline) would serialize as
            # the non-standard JSON token Infinity and break strict --json
            # consumers (jq, JSON.parse); null keeps the report parseable
            # while "regressed" still carries the verdict
            "delta": None if math.isinf(delta) else round(delta, 4),
            "worsening": None if math.isinf(worsening)
            else round(worsening, 4),
            "regressed": regressed,
            "description": desc,
        }
        if regressed:
            regressions.append(name)
    if not rows:
        verdict = VERDICT_NO_HISTORY
    elif regressions:
        verdict = VERDICT_REGRESSION
    else:
        verdict = VERDICT_OK
    return {
        "verdict": verdict,
        "threshold": threshold,
        "regressions": regressions,
        "metrics": rows,
        "history_total": len(history),
        "history_usable": len(usable),
    }


def format_compare(report: Dict[str, Any],
                   history_dir: Optional[str] = None) -> str:
    lines: List[str] = []
    add = lines.append
    add("=== dstpu cross-run regression check ===")
    if history_dir:
        add(f"history: {report['history_usable']}/{report['history_total']} "
            f"usable run(s) under {history_dir}")
    add(f"threshold: {report['threshold'] * 100:.0f}% vs history median")
    rows = report["metrics"]
    if rows:
        add(f"{'metric':<26}{'current':>12}{'baseline':>12}{'delta':>9}"
            f"{'n':>4}  verdict")
        for name, r in rows.items():
            verdict = "REGRESSED" if r["regressed"] else "ok"
            delta = "inf%" if r["delta"] is None \
                else f"{r['delta'] * 100:.1f}%"
            add(f"{name:<26}{r['current']:>12.4g}{r['baseline']:>12.4g}"
                f"{delta:>9}{r['n_history']:>4}  {verdict}")
    else:
        add("(no comparable metrics between the current run and history)")
    add(f"verdict: {report['verdict'].upper()}")
    if report["regressions"]:
        add("regressed: " + ", ".join(
            f"{n} ({rows[n]['description']})" for n in report["regressions"]))
    return "\n".join(lines)
