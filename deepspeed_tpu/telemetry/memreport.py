"""``dstpu-mem`` — render the memory observability plane for humans.

Three reports, all host-side, all from data the serve tier already
records (no new device work):

  * **occupancy ledger table** — the ``MemoryLedger`` bucket breakdown
    (params / kv_pages / decode_workspace / ...) with the conservation
    verdict, scraped live from a running ``dstpu-serve`` or
    ``dstpu-router`` ``/memory`` endpoint (``--url``);
  * **KV page-heat report** — a text heatmap of the block pool (one
    glyph per page, banded by age-since-last-touch), the age histogram,
    the cold-set sizes at each configured threshold and the per-tenant
    footprint table (fractional bytes for radix-shared pages);
  * **what-if-spill table** — from a *recorded* heat trace (the
    ``kv_heat`` events the serve loop emits into ``events.jsonl``), an
    offline estimate of what a host-offload tier would buy: for each
    candidate (age threshold, host budget) pair, the peak spillable cold
    set, the estimated host hit rate, and the decode tokens whose
    recompute the tier would avoid.  This is the staging report for the
    ROADMAP memory-tiering item: it names the cold set *before* anyone
    builds the spiller.

The estimator is deliberately simple and conservative:

  * a page is *spillable at threshold A* when its age-since-touch is
    >= A windows; the peak of that count across the trace sizes the
    host tier (``peak_cold_pages`` / ``peak_cold_mb``);
  * every *retouch* of a page that had been cold past A (the tracker's
    cumulative ``retouch_ages`` histogram) is a would-be host hit — had
    the page been spilled, the host copy would have served it instead
    of a recompute of ``block_size`` tokens;
  * the host tier holds ``host_mb`` worth of pages; when the peak cold
    set exceeds it we scale the hit rate down linearly
    (``min(1, host_pages / peak_cold_pages)``) — no cleverness about
    which pages to keep.

Usage::

    dstpu-mem TELEMETRY_DIR [--thresholds 4,16,64] [--host-mb 1,4,16]
    dstpu-mem --url http://HOST:PORT [--json OUT]
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

MB = 1024.0 * 1024.0

#: heatmap glyph bands: (min age, glyph).  ``.`` is a free page.
_HEAT_BANDS = ((64, " "), (16, "-"), (4, "="), (1, "+"), (0, "#"))
_HEAT_LEGEND = "#=age0  +=1-3  ==4-15  -=16-63  (blank)=64+  .=free"
_HEAT_COLS = 64


def _fmt_bytes(n: float) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


# --------------------------------------------------------------------- #
# Data sources
# --------------------------------------------------------------------- #
def fetch_snapshot(url: str, timeout_s: float = 10.0) -> Dict[str, Any]:
    """GET ``/memory`` from a live dstpu-serve or dstpu-router."""
    url = url.rstrip("/")
    if "://" not in url:
        url = "http://" + url
    with urllib.request.urlopen(f"{url}/memory", timeout=timeout_s) as r:
        return json.loads(r.read())


def read_heat_trace(telemetry_dir: str) -> List[Dict[str, Any]]:
    """All ``kv_heat`` events from a recorded telemetry dir (rotation
    aware)."""
    import os

    from .events import read_event_segments
    from .hub import EVENTS_FILE

    path = os.path.join(telemetry_dir, EVENTS_FILE)
    return [e for e in read_event_segments(path)
            if e.get("kind") == "kv_heat"]


# --------------------------------------------------------------------- #
# Renderers (each returns a list of lines)
# --------------------------------------------------------------------- #
def render_ledger(snap: Dict[str, Any]) -> List[str]:
    """The occupancy-ledger bucket table from a ``/memory`` snapshot
    (single replica) or a fleet rollup."""
    buckets = snap.get("buckets") or {}
    if not buckets:
        return []
    live = float(snap.get("live_bytes") or 0.0)
    lines = ["--- HBM occupancy ledger ---"]
    who = snap.get("component") or ""
    procs = snap.get("processes")
    head = f"live {_fmt_bytes(live)}"
    if who:
        head = f"{who}: " + head
    if procs:
        head += f" across {procs} process(es)"
    una = float(snap.get("unattributed_bytes") or 0.0)
    conserved = snap.get("conserved")
    if conserved is None and "nonconserved_processes" in snap:
        conserved = not snap.get("nonconserved_processes")
    head += f" · unattributed {_fmt_bytes(abs(una))}"
    if conserved is not None:
        head += " (conserved)" if conserved else " (NOT CONSERVED)"
    lines.append(head)
    lines.append(f"{'bucket':<20}{'bytes':>12}{'% live':>9}")
    for b in sorted(buckets, key=lambda b: buckets[b] or 0, reverse=True):
        v = float(buckets[b] or 0.0)
        pct = f"{100 * v / live:.1f}%" if live > 0 else "-"
        lines.append(f"{b:<20}{_fmt_bytes(v):>12}{pct:>9}")
    return lines


def render_heat(kv: Dict[str, Any]) -> List[str]:
    """Heatmap + histogram + tenant table from one kv snapshot (either a
    live ``/memory`` body's ``kv`` section or one ``kv_heat`` event)."""
    if not kv:
        return []
    lines = ["--- KV page heat ---"]
    total = int(kv.get("total_pages") or 0)
    lines.append(
        f"window {int(kv.get('window') or 0)} · live "
        f"{int(kv.get('live_pages') or 0)}/{total} pages "
        f"(peak {int(kv.get('peak_live_pages') or 0)}) · used "
        f"{_fmt_bytes(kv.get('used_bytes') or 0)} · "
        f"{int(kv.get('touches_total') or 0)} touches")
    shared = int(kv.get("shared_pages") or 0)
    saved = float(kv.get("prefix_shared_bytes_saved") or 0.0)
    if shared:
        lines.append(f"prefix sharing: {shared} shared pages save "
                     f"{_fmt_bytes(saved)}")
    ages = kv.get("page_ages")
    if ages:
        lines.append(f"heatmap ({_HEAT_LEGEND}):")
        row = []
        for i, a in enumerate(ages):
            if a is None or a < 0:
                row.append(".")
            else:
                row.append(next(g for lo, g in _HEAT_BANDS if a >= lo))
            if len(row) == _HEAT_COLS or i == len(ages) - 1:
                lines.append(f"  [{i - len(row) + 1:>5}] " + "".join(row))
                row = []
    hist = kv.get("age_histogram") or {}
    if hist:
        lines.append("age histogram (windows-since-touch: pages): " +
                     ", ".join(f"{k}:{v}" for k, v in
                               sorted(hist.items(),
                                      key=lambda kv_: int(kv_[0]))))
    cold = kv.get("cold_pages") or {}
    page_bytes = float(kv.get("page_bytes") or 0.0)
    for thr in sorted(cold, key=int):
        n = int(cold[thr] or 0)
        lines.append(f"cold set at age>={thr}: {n} pages "
                     f"({_fmt_bytes(n * page_bytes)})")
    tenants = kv.get("tenants") or {}
    if tenants:
        lines.append(f"{'tenant':<20}{'pages':>10}{'bytes':>12}")
        for t in sorted(tenants,
                        key=lambda t: tenants[t].get("bytes", 0),
                        reverse=True):
            row = tenants[t]
            lines.append(f"{t:<20}{row.get('pages', 0):>10.2f}"
                         f"{_fmt_bytes(row.get('bytes', 0)):>12}")
    return lines


def what_if_spill(events: Sequence[Dict[str, Any]],
                  thresholds: Optional[Sequence[int]] = None,
                  host_mb: Optional[Sequence[float]] = None,
                  ) -> List[Dict[str, Any]]:
    """The what-if-spill estimate; rows of plain dicts so tests and the
    gate can assert on the numbers directly."""
    evs = [e for e in events if e.get("page_bytes")]
    if not evs:
        return []
    final = evs[-1]
    page_bytes = float(final["page_bytes"])
    block_size = int(final.get("block_size") or 0)
    retouch = {int(k): int(v)
               for k, v in (final.get("retouch_ages") or {}).items()}
    if not thresholds:
        thresholds = sorted(int(k)
                            for k in (final.get("cold_pages") or {}))
        thresholds = [t for t in thresholds if t > 0] or [4, 16, 64]
    # Peak spillable set per threshold, across the whole trace.  Use the
    # raw per-page ages when the recorder kept them (pool small enough),
    # else the precomputed cold counts at the configured thresholds.
    peak_cold: Dict[int, int] = {}
    for thr in thresholds:
        peak = 0
        for e in evs:
            ages = e.get("page_ages")
            if ages is not None:
                n = sum(1 for a in ages if a is not None and a >= thr)
            else:
                n = int((e.get("cold_pages") or {}).get(str(thr), 0))
            peak = max(peak, n)
        peak_cold[thr] = peak
    if not host_mb:
        base = max(peak_cold.values()) * page_bytes / MB
        host_mb = sorted({round(max(base * f, 0.25), 2)
                          for f in (0.25, 0.5, 1.0)})
    rows: List[Dict[str, Any]] = []
    for thr in thresholds:
        retouches = sum(v for a, v in retouch.items() if a >= thr)
        for h in host_mb:
            host_pages = int(h * MB // page_bytes) if page_bytes else 0
            if peak_cold[thr] > 0:
                hit = min(1.0, host_pages / peak_cold[thr])
            else:
                hit = 1.0
            rows.append({
                "age_threshold": int(thr),
                "host_mb": float(h),
                "peak_cold_pages": peak_cold[thr],
                "peak_cold_mb": round(peak_cold[thr] * page_bytes / MB,
                                      3),
                "host_pages": host_pages,
                "est_hit_rate": round(hit, 3),
                "cold_retouches": retouches,
                "avoided_recompute_tokens":
                    int(retouches * block_size * hit),
            })
    return rows


def validate_swap(snap: Dict[str, Any],
                  events: Sequence[Dict[str, Any]],
                  thresholds: Optional[Sequence[int]] = None,
                  factor: float = 1.5) -> Dict[str, Any]:
    """``--validate``: judge the LIVE spiller's measured hit rate against
    the what-if prediction computed from the same heat trace at the
    tier's actual capacity.  Passes when the ratio measured/predicted is
    within ``[1/factor, factor]`` — the estimator earned its keep if the
    spiller it sized lands near its forecast.  Returns a verdict dict
    (``ok``/``measured``/``predicted``/``ratio``/``reason``)."""
    swap = snap.get("swap")
    if not isinstance(swap, dict):
        return {"ok": False, "reason": "no swap section in /memory "
                                       "snapshot (host tier off?)"}
    measured = float(swap.get("hit_rate") or 0.0)
    cap_mb = float(swap.get("host_capacity_bytes") or 0) / MB
    rows = what_if_spill(events, thresholds=thresholds,
                         host_mb=[max(cap_mb, 0.01)])
    if not rows:
        return {"ok": False, "reason": "no usable kv_heat events in the "
                                       "trace (nothing to predict from)"}
    # smallest threshold = largest cold set = the conservative forecast
    row = min(rows, key=lambda r: r["age_threshold"])
    predicted = max(float(row["est_hit_rate"]), 1e-6)
    ratio = measured / predicted
    ok = (1.0 / factor) <= ratio <= factor
    return {"ok": ok, "measured": round(measured, 4),
            "predicted": round(predicted, 4), "ratio": round(ratio, 4),
            "factor": float(factor), "host_mb": round(cap_mb, 3),
            "age_threshold": row["age_threshold"],
            "swapped_in": int(swap.get("swapped_in") or 0),
            "misses": int(swap.get("misses") or 0),
            "reason": "measured within factor of prediction" if ok else
                      f"measured {measured:.3f} vs predicted "
                      f"{predicted:.3f} (ratio {ratio:.2f} outside "
                      f"[{1 / factor:.2f}, {factor:.2f}])"}


def render_what_if(rows: Sequence[Dict[str, Any]]) -> List[str]:
    if not rows:
        return []
    lines = ["--- what-if host-offload spill (offline, from heat "
             "trace) ---"]
    lines.append(f"{'age>=':>6}{'host MB':>9}{'cold pages':>12}"
                 f"{'cold MB':>9}{'hit rate':>10}{'retouches':>11}"
                 f"{'avoided tok':>13}")
    for r in rows:
        lines.append(
            f"{r['age_threshold']:>6}{r['host_mb']:>9.2f}"
            f"{r['peak_cold_pages']:>12}{r['peak_cold_mb']:>9.3f}"
            f"{r['est_hit_rate']:>10.2f}{r['cold_retouches']:>11}"
            f"{r['avoided_recompute_tokens']:>13}")
    # Name the concrete staging target: the biggest spillable set.
    best = max(rows, key=lambda r: r["peak_cold_mb"])
    lines.append(
        f"spillable cold set: {best['peak_cold_pages']} pages "
        f"({best['peak_cold_mb']:.3f} MB) at age>="
        f"{best['age_threshold']} windows")
    return lines


# --------------------------------------------------------------------- #
def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu-mem",
        description="Memory observability reports: HBM occupancy "
                    "ledger, KV page heat, what-if-spill staging.")
    p.add_argument("telemetry_dir", nargs="?",
                   help="recorded telemetry dir (reads kv_heat events "
                        "from events.jsonl)")
    p.add_argument("--url", help="live dstpu-serve/dstpu-router base "
                                 "URL; GETs /memory")
    p.add_argument("--thresholds",
                   help="comma-separated cold-age thresholds (windows) "
                        "for the what-if table")
    p.add_argument("--host-mb",
                   help="comma-separated candidate host-tier sizes (MB)")
    p.add_argument("--json", dest="json_out",
                   help="also write the machine-readable report here")
    p.add_argument("--validate", action="store_true",
                   help="compare the live spiller's measured swap hit "
                        "rate (--url /memory swap section) against the "
                        "what-if prediction from TELEMETRY_DIR's heat "
                        "trace; exit 1 when outside --validate-factor")
    p.add_argument("--validate-factor", type=float, default=1.5,
                   help="accepted measured/predicted ratio band "
                        "[1/F, F] (default 1.5)")
    args = p.parse_args(argv)
    if not args.telemetry_dir and not args.url:
        p.error("need a TELEMETRY_DIR and/or --url")
    if args.validate and not (args.telemetry_dir and args.url):
        p.error("--validate needs BOTH a TELEMETRY_DIR (the recorded "
                "heat trace) and --url (the live spiller)")

    thresholds = ([int(x) for x in args.thresholds.split(",") if x]
                  if args.thresholds else None)
    host_mb = ([float(x) for x in args.host_mb.split(",") if x]
               if args.host_mb else None)

    lines: List[str] = []
    report: Dict[str, Any] = {}
    if args.url:
        try:
            snap = fetch_snapshot(args.url)
        except Exception as e:  # noqa: BLE001 — CLI surface
            print(f"dstpu-mem: cannot fetch {args.url}/memory: {e!r}",
                  file=sys.stderr)
            return 1
        report["snapshot"] = snap
        lines += render_ledger(snap)
        kv = snap.get("kv") or {}
        if kv:
            lines.append("")
            lines += render_heat(kv)
    if args.telemetry_dir:
        events = read_heat_trace(args.telemetry_dir)
        if not events:
            print(f"dstpu-mem: no kv_heat events under "
                  f"{args.telemetry_dir}", file=sys.stderr)
            if not args.url:
                return 1
        else:
            if lines:
                lines.append("")
            lines += [f"heat trace: {len(events)} kv_heat events from "
                      f"{args.telemetry_dir}"]
            lines += render_heat(events[-1])
            rows = what_if_spill(events, thresholds=thresholds,
                                 host_mb=host_mb)
            report["what_if"] = rows
            report["trace_events"] = len(events)
            if rows:
                lines.append("")
                lines += render_what_if(rows)
    rc = 0
    if args.validate:
        verdict = validate_swap(report.get("snapshot") or {},
                                read_heat_trace(args.telemetry_dir),
                                thresholds=thresholds,
                                factor=args.validate_factor)
        report["validate"] = verdict
        lines.append("")
        lines.append("--- swap hit-rate validation ---")
        if "measured" in verdict:
            lines.append(
                f"measured {verdict['measured']:.3f} vs predicted "
                f"{verdict['predicted']:.3f} at {verdict['host_mb']:.2f}"
                f" MB (age>={verdict['age_threshold']}, ratio "
                f"{verdict['ratio']:.2f}, band ±{verdict['factor']}x)")
        lines.append(("PASS: " if verdict["ok"] else "FAIL: ")
                     + verdict["reason"])
        rc = 0 if verdict["ok"] else 1
    print("\n".join(lines))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
