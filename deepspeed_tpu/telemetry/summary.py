"""Run-summary: turn a telemetry output directory into a human report.

Backs the ``bin/dstpu-telemetry`` CLI.  Reads the ``events.jsonl`` written by
a run (spans, metric snapshots, structured events) — with ``trace.json`` as a
span fallback for logs that predate the JSONL span mirror — and prints:

  * a step-phase time breakdown (count / total / mean / p50 / p95 per span);
  * a per-collective communication table (calls, bytes, latency, alg/bus
    bandwidth estimates);
  * performance attribution: the profiler's per-module cost tree
    (``profile_report`` events), the roofline/MFU line (``roofline/*``
    gauges), and a device-time breakdown parsed from the captured xprof
    trace (``xprof_trace`` events / ``--xprof``);
  * memory high-water marks (live jax.Arrays + device allocator stats);
  * an incident digest (faults, watchdog timeouts, stragglers, checkpoint
    lifecycle).

Everything is computed into a plain dict first (``summarize_run``) so tests
and downstream tooling can consume the numbers without scraping text.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .events import read_event_segments
from .metrics import _percentile

EVENT_KINDS_INCIDENT = ("fault", "watchdog_timeout", "elastic_worker_failure",
                        "elastic_restart", "elastic_reshape", "straggler",
                        "anomaly", "anomaly_checkpoint_failed",
                        "checkpoint_reshard_fallback",
                        "serving_nan_isolated", "serving_window_hang",
                        "fleet_replica_lost", "fleet_mid_stream_error",
                        "fleet_prefill_fallback", "fleet_tenant_shed",
                        "fleet_scale_up", "fleet_scale_down", "fleet_heal",
                        "fleet_controller_crash", "mem_unattributed")

#: request-tracing counters (telemetry/tracing/store.py mirrors these)
TRACE_COUNTERS = ("trace/started", "trace/finished", "trace/kept",
                  "trace/dropped", "trace/upgraded", "trace/flagged")

#: goodput-ledger category order for the rendered table (telemetry/goodput.py
#: is canonical; imported lazily in goodput_summary so a partial install of
#: the telemetry package still summarizes everything else)
GOODPUT_SCALARS = ("wall_s", "goodput_fraction", "overcommit_s")

#: roofline table columns, shared between the section renderer and --help
ROOFLINE_COLUMNS = (
    ("achieved_tflops", "achieved TFLOP/s per chip (step flops / step time)"),
    ("peak_tflops", "device bf16 peak TFLOP/s (profiling/roofline.py table)"),
    ("mfu", "model flops utilization = achieved / peak"),
    ("hbm_gbps", "achieved HBM bandwidth, GB/s per chip"),
    ("hbm_utilization", "achieved / peak HBM bandwidth"),
    ("arithmetic_intensity", "flops per byte accessed; above the ridge "
                             "point the step is compute-bound"),
)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024.0
    return f"{n:.2f} TB"


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:.2f}"


# --------------------------------------------------------------------- #
# Loaders
# --------------------------------------------------------------------- #
def load_run(events_path: Optional[str],
             trace_path: Optional[str] = None) -> Dict[str, Any]:
    """Parse the raw artifacts into {spans, metrics, events}.

    ``metrics``: metric snapshots are cumulative, so only the LAST snapshot
    row per (name, labelset) counts.
    """
    spans: List[Dict[str, Any]] = []
    metrics: Dict[tuple, Dict[str, Any]] = {}
    events: List[Dict[str, Any]] = []
    runs = 0
    if events_path:
        # rotation-aware: a size-rotated log's oldest events live in
        # events.jsonl.N segments — walk them oldest-first so the stream
        # (and the latest run_start marker) reads exactly as written
        for rec in read_event_segments(events_path):
            kind = rec.get("kind")
            if kind == "run_start":
                # append-mode log: summarize only the LATEST run, consistent
                # with trace.json (which the last run overwrote)
                runs += 1
                spans.clear()
                metrics.clear()
                events.clear()
                continue
            if kind == "span":
                spans.append(rec)
            elif kind == "metric":
                labels = rec.get("labels") or {}
                key = (rec.get("name"),
                       tuple(sorted((str(k), str(v))
                                    for k, v in labels.items())))
                metrics[key] = rec
            else:
                events.append(rec)
    if not spans and trace_path and os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                trace = json.load(f)
            for ev in trace.get("traceEvents", []):
                if ev.get("ph") != "X":
                    continue
                spans.append({
                    "name": ev.get("name", "?"),
                    "start_s": float(ev.get("ts", 0.0)) / 1e6,
                    "dur_s": float(ev.get("dur", 0.0)) / 1e6,
                    "depth": 0,
                    "parent": (ev.get("args") or {}).get("parent"),
                })
        except (OSError, json.JSONDecodeError, ValueError):
            pass
    return {"spans": spans, "metrics": list(metrics.values()),
            "events": events, "runs_in_log": max(runs, 1)}


# --------------------------------------------------------------------- #
# Sections
# --------------------------------------------------------------------- #
def step_breakdown(spans: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    groups: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    for s in spans:
        name = s.get("name", "?")
        groups.setdefault(name, []).append(float(s.get("dur_s", 0.0)))
        if s.get("error"):
            errors[name] = errors.get(name, 0) + 1
    rows = []
    for name, durs in groups.items():
        durs_sorted = sorted(durs)
        total = sum(durs)
        rows.append({
            "phase": name,
            "count": len(durs),
            "total_s": total,
            "mean_s": total / len(durs),
            "p50_s": _percentile(durs_sorted, 50),
            "p95_s": _percentile(durs_sorted, 95),
            "max_s": durs_sorted[-1],
            "errors": errors.get(name, 0),
        })
    rows.sort(key=lambda r: r["total_s"], reverse=True)
    return rows


def _metric_map(metrics: Sequence[Dict[str, Any]],
                name: str) -> Dict[tuple, Dict[str, Any]]:
    out = {}
    for m in metrics:
        if m.get("name") == name:
            labels = m.get("labels") or {}
            out[tuple(sorted(labels.items()))] = m
    return out


def comm_table(metrics: Sequence[Dict[str, Any]],
               device_kind: Optional[str] = None) -> List[Dict[str, Any]]:
    calls = _metric_map(metrics, "comm/calls")
    sizes = _metric_map(metrics, "comm/bytes")
    lats = _metric_map(metrics, "comm/latency_s")
    algbw = _metric_map(metrics, "comm/algbw_gbps")
    busbw = _metric_map(metrics, "comm/busbw_gbps")
    ranks = _metric_map(metrics, "comm/ranks")
    # per-collective bandwidth roofline: achieved bus bandwidth vs the
    # device kind's aggregate interconnect peak (profiling/roofline.py)
    ici_peak_gbps = None
    if device_kind:
        try:
            from ..profiling.roofline import interconnect_peak

            peak = interconnect_peak(device_kind)
            ici_peak_gbps = peak / 1e9 if peak > 0 else None
        except Exception:  # noqa: BLE001 — table degrades, never dies
            ici_peak_gbps = None
    ops = sorted({k for k in list(calls) + list(sizes)})
    rows = []
    for key in ops:
        op = dict(key).get("op", "?")
        size = sizes.get(key, {})
        lat = lats.get(key, {})
        bus = busbw.get(key, {}).get("mean")
        pct_peak = None
        if bus and ici_peak_gbps:
            pct_peak = 100.0 * float(bus) / ici_peak_gbps
        rows.append({
            "op": op,
            "calls": int(calls.get(key, {}).get("value", 0)),
            "bytes_total": size.get("sum", 0),
            "bytes_mean": size.get("mean", 0),
            "bytes_max": size.get("max", 0),
            "latency_total_s": lat.get("sum", 0),
            "latency_mean_s": lat.get("mean", 0),
            "algbw_mean_gbps": algbw.get(key, {}).get("mean"),
            "busbw_mean_gbps": bus,
            "busbw_pct_peak": pct_peak,
            "ici_peak_gbps": ici_peak_gbps,
            "ranks": ranks.get(key, {}).get("value"),
        })
    rows.sort(key=lambda r: r["bytes_total"] or 0, reverse=True)
    return rows


#: collective algorithm/wire selection gauges (overlap manager,
#: runtime/comm/hierarchical.py) — exact names, distinct from the
#: labelled per-op comm facade series (comm/calls, comm/bytes, …)
COMM_SELECTION_GAUGES = ("comm/algo_2hop", "comm/wire_bits",
                         "comm/predicted_exchange_ms",
                         "comm/predicted_wire_bytes")


def overlap_summary(metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``overlap/*`` gauges (comm/compute overlap subsystem): exposed
    comm fraction, deferred-reduction activity, bucket shape — plus the
    collective algorithm/wire selection (``comm/*`` gauges) under
    ``comm_selection``."""
    out: Dict[str, Any] = {}
    comm: Dict[str, Any] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if name.startswith("overlap/"):
            key = name.split("/", 1)[1]
            out[key] = m.get("value", m.get("count"))
        elif name in COMM_SELECTION_GAUGES:
            comm[name.split("/", 1)[1]] = m.get("value")
    if comm:
        out["comm_selection"] = comm
    return out


#: request-lifecycle counters surfaced in the serving section / incident
#: digest (LifecycleScheduler mirrors these into the registry)
SERVING_LIFECYCLE_COUNTERS = (
    "serving/requests", "serving/completed", "serving/shed",
    "serving/preempted", "serving/cancelled", "serving/deadline_expired",
    "serving/ttft_timeout", "serving/nan_isolated", "serving/window_hang",
    "serving/rejected", "serving/drain_expired",
    "serving/spec_windows", "serving/spec_drafted", "serving/spec_accepted",
    "serving/prefix_hits", "serving/prefix_hit_tokens",
    "serving/kv_import", "serving/kv_import_tokens",
    "serving/prefill_exported")

#: serving latency histograms: TTFT (arrival → first generated token) and
#: TPOT (decode-phase seconds per output token)
SERVING_LATENCY_HISTOGRAMS = ("serving/ttft_s", "serving/tpot_s")


def serving_summary(metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``serving/*`` series: decode-HBM-roofline gauges (published per
    drained decode window by ``InferenceEngineV2._record_decode_roofline``)
    with the per-kernel %-of-peak breakdown, plus the request-lifecycle
    layer — shed/preempt/cancel/expiry counters and TTFT/TPOT percentiles
    (published by ``LifecycleScheduler``)."""
    out: Dict[str, Any] = {}
    kernels: Dict[str, Dict[str, Any]] = {}
    lifecycle: Dict[str, float] = {}
    latency: Dict[str, Dict[str, Any]] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if not name.startswith("serving/"):
            continue
        key = name.split("/", 1)[1]
        labels = m.get("labels") or {}
        if labels.get("device"):
            out["device_kind"] = labels["device"]
        if name in SERVING_LIFECYCLE_COUNTERS:
            lifecycle[key] = m.get("value")
        elif name in SERVING_LATENCY_HISTOGRAMS:
            if m.get("count"):
                latency[key] = {k: m.get(k) for k in
                                ("count", "mean", "p50", "p90", "p95",
                                 "p99", "max")}
        elif key.startswith("kernel_"):
            kname = labels.get("kernel", "?")
            kernels.setdefault(kname, {})[key[len("kernel_"):]] = \
                m.get("value")
        else:
            out[key] = m.get("value")
    if kernels:
        out["kernels"] = kernels
    if lifecycle:
        out["lifecycle"] = lifecycle
    if latency:
        out["latency"] = latency
    return out


def kernels_summary(metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``kernels/*`` series: per-kernel %-of-peak rooflines
    (``profiling/roofline.py publish_kernel_gauges`` — published from the
    engine per decode window like the ``serving/*`` gauges, and by the
    ``kernel_sweep`` bench).  One row per kernel label."""
    out: Dict[str, Dict[str, Any]] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if not name.startswith("kernels/"):
            continue
        key = name.split("/", 1)[1]
        labels = m.get("labels") or {}
        kname = labels.get("kernel")
        if not kname:
            continue
        row = out.setdefault(kname, {})
        row[key] = m.get("value")
        if labels.get("device"):
            row["device_kind"] = labels["device"]
    # "bound" is a string the numeric gauges can't carry — reconstruct it
    # from the published arithmetic intensity vs the device's ridge
    for row in out.values():
        ai = row.get("arithmetic_intensity")
        if isinstance(ai, (int, float)) and row.get("device_kind"):
            from ..profiling.roofline import spec_for_kind

            ridge = spec_for_kind(row["device_kind"]).ridge_intensity
            row["bound"] = "compute" if ai >= ridge else "memory"
    return out


#: fleet-tier counters (dstpu-router) surfaced in the fleet section
FLEET_COUNTERS = (
    "fleet/routed", "fleet/rerouted", "fleet/shed", "fleet/replica_shed",
    "fleet/replica_lost", "fleet/mid_stream_error",
    "fleet/prefill_disagg", "fleet/prefill_fallback",
    "fleet/kv_ship_bytes",
    # per-tenant QoS + the autoscaling controller (dstpu-fleet)
    "fleet/tenant_shed",
    "fleet/controller_scale_ups", "fleet/controller_scale_downs",
    "fleet/controller_heals", "fleet/controller_crashes",
    "fleet/controller_scrape_failures", "fleet/controller_spawn_failures")


def fleet_summary(metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``fleet/*`` series published by ``dstpu-router``: fleet size /
    routability, routed/rerouted/shed/replica-lost counters, the
    aggregated prefix-cache hit rate, per-replica queue depth + KV
    pressure (labelled gauges), and disaggregated-prefill KV-ship
    volume/latency."""
    out: Dict[str, Any] = {}
    counters: Dict[str, float] = {}
    replicas: Dict[str, Dict[str, Any]] = {}
    tenants: Dict[str, Dict[str, Any]] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if not name.startswith("fleet/"):
            continue
        key = name.split("/", 1)[1]
        labels = m.get("labels") or {}
        if labels.get("tenant"):
            tenants.setdefault(labels["tenant"], {})[
                key.replace("tenant_", "")] = m.get("value")
        elif name in FLEET_COUNTERS:
            counters[key] = m.get("value")
        elif labels.get("replica"):
            replicas.setdefault(labels["replica"], {})[
                key.replace("replica_", "")] = m.get("value")
        else:
            out[key] = m.get("value")
    if counters:
        out["counters"] = counters
    if replicas:
        out["replicas"] = replicas
    if tenants:
        out["tenants"] = tenants
    return out


def tracing_summary(metrics: Sequence[Dict[str, Any]],
                    events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The request-tracing plane (telemetry/tracing): per-segment
    TTFT/TPOT decomposition percentiles from the
    ``serving/trace_segment_s`` histogram (one labelset per span kind),
    the tail-sampling counters, and the exemplar links from the TTFT/TPOT
    histogram tails to the trace ids that populated them
    (``trace_exemplar`` events; the ids resolve via ``dstpu-trace
    --request`` or ``GET /traces?request=``)."""
    out: Dict[str, Any] = {}
    segments: Dict[str, Dict[str, Any]] = {}
    counters: Dict[str, float] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if name == "serving/trace_segment_s" and m.get("count"):
            seg = (m.get("labels") or {}).get("segment", "?")
            segments[seg] = {k: m.get(k) for k in
                            ("count", "sum", "mean", "p50", "p95")}
        elif name in TRACE_COUNTERS:
            counters[name.split("/", 1)[1]] = m.get("value")
    # newest exemplar offer per trace id wins; keep the largest few
    exemplars: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.get("kind") != "trace_exemplar":
            continue
        metric, trace = str(e.get("metric")), str(e.get("trace"))
        try:
            exemplars.setdefault(metric, {})[trace] = float(e.get("value"))
        except (TypeError, ValueError):
            continue
    if segments:
        out["segments"] = segments
    if counters:
        out["counters"] = counters
    if exemplars:
        out["exemplars"] = {
            m: [{"trace": t, "value": v} for t, v in
                sorted(vals.items(), key=lambda kv: kv[1],
                       reverse=True)[:4]]
            for m, vals in exemplars.items()}
    return out


def goodput_summary(metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The goodput ledger's ``goodput/*`` gauges (telemetry/goodput.py):
    ledger wall, per-category seconds + fractions-of-wall, the goodput
    scalar (compute / wall), the conservation detector (``overcommit_s``
    — attributed beyond wall means a double-counting seam) and the
    per-tenant shed attribution."""
    from .goodput import GOODPUT_CATEGORIES

    out: Dict[str, Any] = {}
    cats: Dict[str, float] = {}
    tenants: Dict[str, float] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if not name.startswith("goodput/"):
            continue
        key = name.split("/", 1)[1]
        labels = m.get("labels") or {}
        if key == "tenant_shed_s" and labels.get("tenant"):
            tenants[labels["tenant"]] = m.get("value")
        elif key.endswith("_s") and key[:-2] in GOODPUT_CATEGORIES:
            cats[key[:-2]] = m.get("value")
        elif key in GOODPUT_SCALARS:
            out[key] = m.get("value")
    if cats:
        out["categories"] = cats
        wall = float(out.get("wall_s") or 0.0)
        if wall > 0:
            out["fractions"] = {c: round((v or 0.0) / wall, 6)
                                for c, v in cats.items()}
    if tenants:
        out["tenant_shed_s"] = tenants
    return out


def memory_summary(metrics: Sequence[Dict[str, Any]],
                   events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name in ("memory/live_array_bytes", "memory/live_array_count",
                 "memory/device_bytes_in_use",
                 "memory/device_peak_bytes_in_use"):
        for m in metrics:
            if m.get("name") == name and m.get("count"):
                out[name.split("/", 1)[1] + "_max"] = m.get("max")
    # which step hit the live-bytes peak (from per-step memory events)
    peak, peak_step = -1.0, None
    for e in events:
        if e.get("kind") != "memory":
            continue
        v = e.get("live_array_bytes")
        if v is not None and float(v) > peak:
            peak, peak_step = float(v), e.get("step")
    if peak_step is not None:
        out["live_array_bytes_peak_step"] = peak_step
    # HBM occupancy ledger (``mem/*`` gauges, telemetry/memory.py): bucket
    # bytes, the conservation detector and the KV heat cold-set view
    from .memory import MEM_BUCKETS

    buckets: Dict[str, Any] = {}
    kv: Dict[str, Any] = {}
    cold: Dict[str, Any] = {}
    tenants: Dict[str, Any] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if not name.startswith("mem/"):
            continue
        key = name.split("/", 1)[1]
        labels = m.get("labels") or {}
        if key.endswith("_bytes") and key[:-6] in MEM_BUCKETS:
            buckets[key[:-6]] = m.get("value")
        elif key == "kv_cold_pages" and labels.get("age_windows"):
            cold[labels["age_windows"]] = m.get("value")
        elif key == "tenant_kv_bytes" and labels.get("tenant"):
            tenants[labels["tenant"]] = m.get("value")
        elif key in ("live_bytes", "unattributed_bytes",
                     "unattributed_frac", "conserved"):
            out[key] = m.get("value")
        elif key in ("kv_live_pages", "kv_peak_pages", "kv_used_bytes",
                     "prefix_shared_bytes_saved"):
            kv[key] = m.get("value")
    if buckets:
        out["buckets"] = buckets
    if cold:
        kv["cold_pages"] = cold
    if tenants:
        kv["tenants"] = tenants
    if kv:
        out["kv"] = kv
    return out


def profile_summary(events: Sequence[Dict[str, Any]],
                    metrics: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Performance attribution: the last ``profile_report`` event (module
    rows + roofline snapshot at profile time) plus the latest ``roofline/*``
    gauges (steady-state MFU, updated every roofline_interval steps)."""
    out: Dict[str, Any] = {}
    for e in events:
        if e.get("kind") == "profile_report":
            out["report"] = {k: v for k, v in e.items() if k != "kind"}
    gauges: Dict[str, Any] = {}
    for m in metrics:
        name = str(m.get("name", ""))
        if name.startswith("roofline/"):
            gauges[name.split("/", 1)[1]] = m.get("value")
            labels = m.get("labels") or {}
            if labels.get("device"):
                gauges["device_kind"] = labels["device"]
    if gauges:
        out["roofline_gauges"] = gauges
    return out


def xprof_summary(events: Sequence[Dict[str, Any]],
                  explicit_dir: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Device-time attribution from the captured xprof trace: ``--xprof``
    wins, else the engine's ``xprof_trace`` breadcrumb event."""
    candidates = [explicit_dir] if explicit_dir else []
    for e in events:
        if e.get("kind") == "xprof_trace" and e.get("dir"):
            candidates.append(str(e["dir"]))
    for path in candidates:
        if not path or not os.path.exists(path):
            continue
        try:
            from ..profiling.xprof_parse import attribute_device_time

            report = attribute_device_time(path)
        except Exception:  # noqa: BLE001 — a bad trace must not kill the CLI
            continue
        if report["files"]:
            report["source"] = path
            return report
    return None


def incident_summary(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    counts: Dict[str, int] = {}
    for e in events:
        counts[e.get("kind", "?")] = counts.get(e.get("kind", "?"), 0) + 1
    incidents = [e for e in events if e.get("kind") in EVENT_KINDS_INCIDENT]
    checkpoints = [e for e in events
                   if str(e.get("kind", "")).startswith("checkpoint")]
    return {"event_counts": counts,
            "incidents": incidents[-20:],
            "checkpoints": checkpoints[-20:]}


def summarize_run(events_path: Optional[str],
                  trace_path: Optional[str] = None,
                  xprof_dir: Optional[str] = None) -> Dict[str, Any]:
    run = load_run(events_path, trace_path)
    profile = profile_summary(run["events"], run["metrics"])
    # device kind recorded by the roofline gauges keys the per-collective
    # bandwidth roofline in the comm table
    device_kind = (profile.get("roofline_gauges") or {}).get("device_kind")
    return {
        "sources": {"events": events_path, "trace": trace_path,
                    "xprof": xprof_dir},
        "runs_in_log": run["runs_in_log"],
        "n_spans": len(run["spans"]),
        "step_breakdown": step_breakdown(run["spans"]),
        "comm": comm_table(run["metrics"], device_kind=device_kind),
        "overlap": overlap_summary(run["metrics"]),
        "kernels": kernels_summary(run["metrics"]),
        "serving": serving_summary(run["metrics"]),
        "fleet": fleet_summary(run["metrics"]),
        "goodput": goodput_summary(run["metrics"]),
        "tracing": tracing_summary(run["metrics"], run["events"]),
        "profile": profile,
        "xprof": xprof_summary(run["events"], explicit_dir=xprof_dir),
        "memory": memory_summary(run["metrics"], run["events"]),
        "incidents": incident_summary(run["events"]),
    }


# --------------------------------------------------------------------- #
# Rendering
# --------------------------------------------------------------------- #
def format_summary(s: Dict[str, Any]) -> str:
    lines: List[str] = []
    add = lines.append
    add("=== dstpu telemetry run summary ===")
    add(f"sources: events={s['sources']['events']} "
        f"trace={s['sources']['trace']}")
    if s.get("runs_in_log", 1) > 1:
        add(f"note: log contains {s['runs_in_log']} runs — summarizing the "
            f"latest only")
    add("")

    add("--- step-phase breakdown ---")
    rows = s["step_breakdown"]
    if rows:
        add(f"{'phase':<32}{'count':>7}{'total(ms)':>12}{'mean(ms)':>11}"
            f"{'p50(ms)':>11}{'p95(ms)':>11}{'max(ms)':>11}{'err':>5}")
        for r in rows:
            add(f"{r['phase']:<32}{r['count']:>7}{_fmt_ms(r['total_s']):>12}"
                f"{_fmt_ms(r['mean_s']):>11}{_fmt_ms(r['p50_s']):>11}"
                f"{_fmt_ms(r['p95_s']):>11}{_fmt_ms(r['max_s']):>11}"
                f"{r['errors']:>5}")
    else:
        add("(no spans recorded)")
    add("")

    add("--- communication ---")
    rows = s["comm"]
    if rows:
        peak = next((r["ici_peak_gbps"] for r in rows
                     if r.get("ici_peak_gbps")), None)
        if peak:
            add(f"interconnect peak: {peak:.0f} GB/s/chip (aggregate ICI; "
                f"%peak = achieved busbw vs this)")
        add(f"{'op':<22}{'calls':>7}{'total':>12}{'mean msg':>12}"
            f"{'lat(ms)':>10}{'algbw(GB/s)':>13}{'busbw(GB/s)':>13}"
            f"{'%peak':>8}")
        for r in rows:
            alg = f"{r['algbw_mean_gbps']:.2f}" if r.get("algbw_mean_gbps") \
                else "-"
            bus = f"{r['busbw_mean_gbps']:.2f}" if r.get("busbw_mean_gbps") \
                else "-"
            pct = f"{r['busbw_pct_peak']:.1f}%" \
                if r.get("busbw_pct_peak") is not None else "-"
            add(f"{r['op']:<22}{r['calls']:>7}"
                f"{_fmt_bytes(r['bytes_total'] or 0):>12}"
                f"{_fmt_bytes(r['bytes_mean'] or 0):>12}"
                f"{_fmt_ms(r['latency_mean_s'] or 0):>10}{alg:>13}{bus:>13}"
                f"{pct:>8}")
    else:
        add("(no collectives recorded)")
    ov = s.get("overlap") or {}
    if ov:
        frac = ov.get("exposed_comm_fraction")
        exposed = f"{float(frac) * 100:.1f}% of device time" \
            if frac is not None else "n/a (no xprof capture)"
        bits = [f"exposed comm: {exposed}"]
        if ov.get("deferred") is not None:
            steps = int(ov.get("deferred_steps") or 0)
            bits.append(f"deferred reduction "
                        f"{'on' if ov['deferred'] else 'off'}"
                        f" ({steps} steps)")
        if ov.get("bucket_count"):
            bits.append(f"buckets {int(ov['bucket_count'])}"
                        f" @ {_fmt_bytes(ov.get('bucket_bytes') or 0)} target")
        if ov.get("prefetch_reuse"):
            bits.append(f"prefetch reuse {int(ov['prefetch_reuse'])}")
        cs = ov.get("comm_selection") or {}
        if cs:
            wb = int(cs.get("wire_bits") or 0)
            bits.append(
                f"collectives "
                f"{'2-hop' if cs.get('algo_2hop') else 'flat'}/"
                f"{f'int{wb}' if wb else 'fp'}")
        add("overlap: " + " · ".join(bits))
    add("")

    add("--- performance attribution ---")
    prof = s.get("profile") or {}
    gauges = prof.get("roofline_gauges")
    report = prof.get("report")
    roof = (report or {}).get("roofline") or gauges
    if roof:
        dev = roof.get("device_kind", "?")
        mfu = roof.get("mfu")
        line = f"roofline [{dev}]: "
        if roof.get("achieved_tflops") is not None:
            line += f"{roof['achieved_tflops']:.1f}"
            if roof.get("peak_tflops"):
                line += f"/{roof['peak_tflops']:.0f}"
            line += " TFLOP/s/chip"
        if mfu is not None:
            line += f" (MFU {mfu * 100:.1f}%)"
        if roof.get("hbm_gbps") is not None:
            line += f", HBM {roof['hbm_gbps']:.0f} GB/s"
            if roof.get("hbm_utilization") is not None:
                line += f" ({roof['hbm_utilization'] * 100:.1f}%)"
        add(line + "  [source: flops profiler]")
    if report:
        add(f"profile @ step {report.get('step')}: "
            f"flops/step={report.get('flops', 0):.3e} "
            f"params={report.get('params', 0):.3e} "
            f"latency={report.get('latency_s', 0):.3f}s")
        rows = report.get("module_rows") or []
        if rows:
            add(f"{'module':<34}{'params':>12}{'flops':>12}{'AI':>8}"
                f"{'%flops':>8}")
            for r in rows:
                label = "  " * int(r.get("depth", 0)) + str(r.get("module"))
                add(f"{label:<34}{r.get('params', 0):>12.3g}"
                    f"{r.get('flops', 0):>12.3g}"
                    f"{r.get('arithmetic_intensity', 0):>8.1f}"
                    f"{r.get('pct_flops', 0):>7.1f}%")
    if not roof and not report:
        add("(no profile_report events — enable config.profiling)")
    xp = s.get("xprof")
    if xp:
        add("")
        add(f"--- device-time breakdown (xprof: {xp.get('source')}) ---")
        from ..profiling.xprof_parse import format_device_table

        for line in format_device_table(xp):
            add(line)
    add("")

    kr = s.get("kernels") or {}
    if kr:
        add("--- kernels (%-of-peak rooflines) ---")
        from ..profiling.roofline import format_kernel_table

        dev = next((row.get("device_kind") for row in kr.values()
                    if row.get("device_kind")), "?")
        add(f"device: {dev}")
        rows = [dict(row, kernel=kname) for kname, row in sorted(
            kr.items(), key=lambda kv: kv[1].get("pct_peak_flops") or 0,
            reverse=True)]
        for line in format_kernel_table(rows):
            add(line)
        add("")

    srv = s.get("serving") or {}
    if srv:
        add("--- serving (decode HBM roofline) ---")
        dev = srv.get("device_kind", "?")
        line = f"decode [{dev}]: "
        if srv.get("decode_tok_per_s") is not None:
            line += f"{srv['decode_tok_per_s']:.1f} tok/s"
        if srv.get("decode_hbm_gbps") is not None:
            line += f", HBM {srv['decode_hbm_gbps']:.1f}"
            if srv.get("peak_hbm_gbps"):
                line += f"/{srv['peak_hbm_gbps']:.0f}"
            line += " GB/s"
            if srv.get("decode_hbm_pct_peak") is not None:
                line += f" ({srv['decode_hbm_pct_peak']:.1f}% of peak)"
        add(line)
        kernels = srv.get("kernels") or {}
        if kernels:
            add(f"{'kernel':<22}{'HBM(GB/s)':>12}{'%peak':>8}")
            for kname in sorted(kernels,
                                key=lambda k: kernels[k].get("hbm_gbps")
                                or 0, reverse=True):
                row = kernels[kname]
                gbps = f"{row['hbm_gbps']:.1f}" \
                    if row.get("hbm_gbps") is not None else "-"
                pct = f"{row['hbm_pct_peak']:.1f}%" \
                    if row.get("hbm_pct_peak") is not None else "-"
                add(f"{kname:<22}{gbps:>12}{pct:>8}")
        if srv.get("acceptance_rate") is not None or \
                srv.get("effective_tok_per_s") is not None:
            # speculative decoding gauges (engine._record_verify_window)
            line = "spec-dec: "
            parts = []
            if srv.get("acceptance_rate") is not None:
                parts.append(f"acceptance {srv['acceptance_rate']:.2f}")
            if srv.get("effective_tok_per_s") is not None:
                parts.append(
                    f"effective {srv['effective_tok_per_s']:.1f} tok/s")
            if srv.get("draft_overhead_frac") is not None:
                parts.append(
                    f"draft overhead "
                    f"{100 * srv['draft_overhead_frac']:.1f}%")
            add(line + ", ".join(parts))
        lat = srv.get("latency") or {}
        for hname, label in (("ttft_s", "TTFT"), ("tpot_s", "TPOT")):
            row = lat.get(hname)
            if row:
                add(f"{label}: p50 {_fmt_ms(row.get('p50') or 0)}ms, "
                    f"p95 {_fmt_ms(row.get('p95') or 0)}ms, "
                    f"p99 {_fmt_ms(row.get('p99') or 0)}ms "
                    f"(n={int(row.get('count') or 0)})")
        lc = srv.get("lifecycle") or {}
        if lc:
            parts = [f"{k}={int(v)}" for k, v in sorted(lc.items())
                     if v]
            if parts:
                add("lifecycle: " + ", ".join(parts))
        add("")

    tr = s.get("tracing") or {}
    if tr:
        add("--- request tracing (TTFT/TPOT decomposition) ---")
        segs = tr.get("segments") or {}
        if segs:
            from .tracing.cli import segment_table_lines

            rows = [{"segment": seg, "count": row.get("count"),
                     "total_s": row.get("sum"), "p50_s": row.get("p50"),
                     "p95_s": row.get("p95")}
                    for seg, row in segs.items()]
            rows.sort(key=lambda r: r["total_s"] or 0, reverse=True)
            for line in segment_table_lines(rows):
                add(line)
        tc = tr.get("counters") or {}
        if tc:
            add("sampling: " + ", ".join(
                f"{k}={int(v)}" for k, v in sorted(tc.items())
                if v is not None))
        for metric, label in (("ttft_s", "TTFT"), ("tpot_s", "TPOT")):
            ex = (tr.get("exemplars") or {}).get(metric)
            if ex:
                add(f"{label} tail exemplars: " + ", ".join(
                    f"{e['trace'][:12]}… ({_fmt_ms(e['value'])}ms)"
                    for e in ex) +
                    "  [dstpu-trace --request <id> / GET /traces]")
        add("")

    fl = s.get("fleet") or {}
    if fl:
        add("--- serving fleet (dstpu-router) ---")
        line = (f"replicas: {int(fl.get('replicas_routable') or 0)}"
                f"/{int(fl.get('replicas_registered') or 0)} routable")
        if fl.get("replicas_saturated"):
            line += f" ({int(fl['replicas_saturated'])} saturated)"
        if fl.get("prefix_hit_rate") is not None:
            line += (f" · prefix-cache hit rate "
                     f"{100 * fl['prefix_hit_rate']:.1f}%"
                     f" ({int(fl.get('prefix_hit_tokens') or 0)} tokens"
                     f" reused)")
        add(line)
        fc = fl.get("counters") or {}
        if fc:
            parts = [f"{k}={int(v)}" for k, v in sorted(fc.items())
                     if v and k != "kv_ship_bytes"]
            if parts:
                add("routing: " + ", ".join(parts))
        if fc.get("kv_ship_bytes") or fl.get("kv_ship_ms") is not None:
            line = "kv ship: " + _fmt_bytes(int(fc.get("kv_ship_bytes")
                                                or 0))
            if fl.get("kv_ship_ms") is not None:
                line += f", last {fl['kv_ship_ms']:.1f}ms"
            if fl.get("kv_ship_tokens"):
                line += f" ({int(fl['kv_ship_tokens'])} tokens)"
            add(line)
        reps = fl.get("replicas") or {}
        if reps:
            add(f"{'replica':<28}{'queue':>7}{'pending':>9}"
                f"{'kv_pressure':>13}{'tok/s pred':>12}")
            for rname in sorted(reps):
                row = reps[rname]
                add(f"{rname:<28}{int(row.get('queue_depth') or 0):>7}"
                    f"{int(row.get('pending') or 0):>9}"
                    f"{(row.get('kv_pressure') or 0):>13.3f}"
                    f"{(row.get('predicted_tok_per_s') or 0):>12.1f}")
        tens = fl.get("tenants") or {}
        if tens:
            add(f"{'tenant':<20}{'admitted':>10}{'shed':>8}"
                f"{'shed rate':>11}{'inflight':>10}")
            for tname in sorted(tens):
                row = tens[tname]
                add(f"{tname:<20}{int(row.get('admitted') or 0):>10}"
                    f"{int(row.get('sheds') or 0):>8}"
                    f"{100 * (row.get('shed_rate') or 0):>10.1f}%"
                    f"{int(row.get('inflight') or 0):>10}")
        if fl.get("controller_replicas") is not None:
            line = (f"controller: {int(fl['controller_replicas'])} live"
                    f" / {int(fl.get('controller_routable') or 0)} routable"
                    f", drain est {fl.get('controller_drain_s') or 0:.2f}s")
            if fl.get("controller_ttft_p95_s") is not None:
                line += f", ttft p95 est {fl['controller_ttft_p95_s']:.2f}s"
            acts = [f"{k.replace('controller_', '')}={int(v)}"
                    for k, v in sorted((fl.get('counters') or {}).items())
                    if k.startswith("controller_") and v]
            if acts:
                line += "  [" + ", ".join(acts) + "]"
            add(line)
        add("")

    gp = s.get("goodput") or {}
    if gp.get("categories"):
        add("--- goodput ledger (every wall-second attributed) ---")
        wall = float(gp.get("wall_s") or 0.0)
        line = f"wall: {wall:.2f}s"
        if gp.get("goodput_fraction") is not None:
            line += f" · goodput {100 * gp['goodput_fraction']:.1f}%"
        over = float(gp.get("overcommit_s") or 0.0)
        line += (f" · overcommit {over:.3f}s"
                 + (" (NOT conserved — double-counted seam?)"
                    if wall > 0 and over > 0.01 * wall else ""))
        add(line)
        cats = gp["categories"]
        fracs = gp.get("fractions") or {}
        add(f"{'category':<20}{'seconds':>12}{'% wall':>9}")
        for cat in sorted(cats, key=lambda c: cats[c] or 0, reverse=True):
            if not cats[cat]:
                continue
            pct = f"{100 * fracs[cat]:.1f}%" if cat in fracs else "-"
            add(f"{cat:<20}{cats[cat]:>12.3f}{pct:>9}")
        tens = gp.get("tenant_shed_s") or {}
        if tens:
            add("shed by tenant: " + ", ".join(
                f"{t}={v:.3f}s" for t, v in sorted(tens.items())))
        add("")

    add("--- memory high-water marks ---")
    mem = s["memory"]
    if mem:
        if "live_array_bytes_max" in mem:
            step = mem.get("live_array_bytes_peak_step")
            at = f" (at step {step})" if step is not None else ""
            add(f"live jax.Arrays: {_fmt_bytes(mem['live_array_bytes_max'])}"
                f"{at}, count max "
                f"{int(mem.get('live_array_count_max') or 0)}")
        if "device_peak_bytes_in_use_max" in mem:
            add(f"device allocator peak: "
                f"{_fmt_bytes(mem['device_peak_bytes_in_use_max'])} "
                f"(in_use max {_fmt_bytes(mem.get('device_bytes_in_use_max') or 0)})")
        buckets = mem.get("buckets") or {}
        if buckets:
            live = float(mem.get("live_bytes") or 0.0)
            line = f"occupancy ledger: live {_fmt_bytes(live)}"
            if mem.get("conserved") is not None:
                ok = bool(mem["conserved"])
                una = float(mem.get("unattributed_bytes") or 0.0)
                line += (f" · unattributed {_fmt_bytes(abs(una))}"
                         + ("" if ok else " (NOT conserved)"))
            add(line)
            add(f"{'bucket':<20}{'bytes':>12}{'% live':>9}")
            for b in sorted(buckets, key=lambda b: buckets[b] or 0,
                            reverse=True):
                v = float(buckets[b] or 0.0)
                if not v:
                    continue
                pct = f"{100 * v / live:.1f}%" if live > 0 else "-"
                add(f"{b:<20}{_fmt_bytes(v):>12}{pct:>9}")
        kv = mem.get("kv") or {}
        if kv:
            line = (f"kv heat: live pages "
                    f"{int(kv.get('kv_live_pages') or 0)} "
                    f"(peak {int(kv.get('kv_peak_pages') or 0)}), used "
                    f"{_fmt_bytes(kv.get('kv_used_bytes') or 0)}")
            saved = float(kv.get("prefix_shared_bytes_saved") or 0.0)
            if saved:
                line += f", prefix sharing saves {_fmt_bytes(saved)}"
            add(line)
            cold = kv.get("cold_pages") or {}
            if cold:
                add("cold pages by age: " + ", ".join(
                    f">{thr}w={int(n)}" for thr, n in
                    sorted(cold.items(), key=lambda kv_: int(kv_[0]))))
            tens = kv.get("tenants") or {}
            if tens:
                add("kv by tenant: " + ", ".join(
                    f"{t}={_fmt_bytes(v)}"
                    for t, v in sorted(tens.items())))
    else:
        add("(no memory samples)")
    add("")

    inc = s["incidents"]
    add("--- events ---")
    add("counts: " + json.dumps(inc["event_counts"], sort_keys=True))
    for e in inc["checkpoints"]:
        dur = e.get("duration_s")
        dur_txt = f" in {dur:.3f}s" if isinstance(dur, (int, float)) else ""
        add(f"  {e.get('kind')}: tag={e.get('tag')}{dur_txt}")
    for e in inc["incidents"]:
        add("  INCIDENT " + json.dumps(
            {k: v for k, v in e.items() if k != "thread_stacks"},
            sort_keys=True, default=str))
    return "\n".join(lines)


# --------------------------------------------------------------------- #
# Postmortem bundle (--bundle)
# --------------------------------------------------------------------- #
def make_bundle(out_path: str,
                events_path: Optional[str] = None,
                trace_path: Optional[str] = None,
                extra_dir: Optional[str] = None,
                summary: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One-file postmortem: every rotation segment of the event log, the
    request-trace log (``traces.jsonl[.N]`` beside it), the chrome trace,
    any ``*config*.json`` echo files in the telemetry dir, plus a
    generated ``summary.json`` (the final metric snapshot, digested) and
    a ``manifest.json`` — packed into ``out_path`` (tar.gz).  Returns the
    manifest.  Missing artifacts are skipped, never fatal: a postmortem
    of a half-dead run is exactly when this gets used."""
    import tarfile
    import time as _time

    from .events import event_segments

    files: List[str] = []
    if events_path:
        files.extend(event_segments(events_path))
        # the request-trace log lives beside events.jsonl in the same
        # telemetry dir (tracing/store.py default wiring)
        files.extend(event_segments(
            os.path.join(os.path.dirname(os.path.abspath(events_path)),
                         "traces.jsonl")))
    if trace_path and os.path.exists(trace_path):
        files.append(trace_path)
    if extra_dir and os.path.isdir(extra_dir):
        for fn in sorted(os.listdir(extra_dir)):
            if "config" in fn and fn.endswith(".json"):
                files.append(os.path.join(extra_dir, fn))
    seen: set = set()
    files = [f for f in files
             if os.path.exists(f) and not (f in seen or seen.add(f))]
    manifest: Dict[str, Any] = {
        "created_unix": _time.time(),
        "sources": {"events": events_path, "trace": trace_path},
        "files": [{"name": os.path.basename(f),
                   "bytes": os.path.getsize(f)} for f in files],
    }
    with tarfile.open(out_path, "w:gz") as tar:
        for f in files:
            tar.add(f, arcname=os.path.join("bundle", os.path.basename(f)))

        def _add_json(name: str, obj: Any) -> None:
            import io

            data = json.dumps(obj, indent=2, sort_keys=True,
                              default=str).encode()
            info = tarfile.TarInfo(os.path.join("bundle", name))
            info.size = len(data)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(data))

        if summary is not None:
            _add_json("summary.json", summary)
            manifest["files"].append({"name": "summary.json",
                                      "generated": True})
        _add_json("manifest.json", manifest)
    return manifest


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #
def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import sys

    roofline_doc = "\n".join(f"  {name:<22}{desc}"
                             for name, desc in ROOFLINE_COLUMNS)
    parser = argparse.ArgumentParser(
        prog="dstpu-telemetry",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description="Summarize a deepspeed_tpu telemetry output directory "
                    "(step-phase breakdown, comm bandwidth, performance "
                    "attribution, memory high-water marks).",
        epilog="roofline columns (the 'performance attribution' section, "
               "from roofline/* gauges\nand profile_report events):\n"
               + roofline_doc +
               "\n\nThe per-module cost tree attributes analytic "
               "flops/bytes to jax.named_scope\nmodules (fwd+bwd), anchored "
               "to XLA cost analysis of the compiled step; the\ndevice-time "
               "breakdown parses the xprof trace captured at "
               "comms_logger.xprof_step\ninto compute / communication / "
               "host-transfer buckets.")
    parser.add_argument("path",
                        help="telemetry output dir (containing events.jsonl/"
                             "trace.json) or a path to an events.jsonl")
    parser.add_argument("--trace", default=None,
                        help="explicit trace.json path (default: "
                             "<dir>/trace.json)")
    parser.add_argument("--xprof", default=None,
                        help="xprof trace dir/file for the device-time "
                             "breakdown (default: the run's xprof_trace "
                             "event, if any)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the summary as JSON instead of text")
    parser.add_argument("--bundle", default=None, metavar="OUT.tar.gz",
                        help="pack a postmortem bundle: events.jsonl[.N] "
                             "+ traces.jsonl[.N] + trace.json + config "
                             "echoes + generated summary.json + manifest, "
                             "as one tar.gz")
    parser.add_argument("--compare", nargs="?", const=".", default=None,
                        metavar="HISTORY_DIR",
                        help="cross-run regression check: diff this run "
                             "(a telemetry dir or a bench JSON) against the "
                             "BENCH_r*.json history in HISTORY_DIR (default "
                             "'.'); exits 3 when a metric regressed past "
                             "the threshold, 2 when either side has "
                             "nothing comparable")
    parser.add_argument("--compare-threshold", type=float, default=0.15,
                        help="relative worsening vs the history median that "
                             "counts as a regression (default 0.15)")
    parser.add_argument("--compare-pattern", default=None,
                        help="history filename glob (default BENCH_r*.json)")
    args = parser.parse_args(argv)

    if args.compare is not None:
        rc, text = _run_compare(args)
        try:
            print(text)
        except BrokenPipeError:
            try:
                sys.stdout.close()
            except BrokenPipeError:
                pass
        return rc

    path = args.path
    if os.path.isdir(path):
        events_path = os.path.join(path, "events.jsonl")
        trace_path = args.trace or os.path.join(path, "trace.json")
    else:
        events_path = path
        trace_path = args.trace
    from .events import event_segments

    # rotation-aware: after a crash mid-rotation the live events.jsonl may
    # be missing while the .N segments hold the whole pre-crash history
    if not event_segments(events_path) and not (
            trace_path and os.path.exists(trace_path)):
        print(f"dstpu-telemetry: no events.jsonl[.N] or trace.json at {path}")
        return 2

    summary = summarize_run(events_path, trace_path, xprof_dir=args.xprof)
    if args.bundle:
        manifest = make_bundle(
            args.bundle, events_path=events_path, trace_path=trace_path,
            extra_dir=path if os.path.isdir(path) else
            os.path.dirname(os.path.abspath(events_path)),
            summary=summary)
        print(f"dstpu-telemetry: bundle {args.bundle} "
              f"({len(manifest['files'])} files)")
        return 0
    try:
        if args.as_json:
            print(json.dumps(summary, indent=2, sort_keys=True, default=str))
        else:
            print(format_summary(summary))
    except BrokenPipeError:   # e.g. piped into `head`
        try:
            sys.stdout.close()
        except BrokenPipeError:
            pass
    return 0


def _run_compare(args) -> Tuple[int, str]:
    """``--compare`` mode: (exit_code, report_text) — 3 on a regression
    (so CI gates on the exit code alone), 2 when there is nothing to
    compare on EITHER side: an unusable current run, or no usable history
    (a mistyped HISTORY_DIR must not read as a green gate).  ``main`` owns
    the printing."""
    from .regression import (DEFAULT_PATTERN, VERDICT_NO_HISTORY,
                             VERDICT_REGRESSION, compare_runs,
                             current_metrics_from_path, format_compare,
                             load_history)

    try:
        current = current_metrics_from_path(args.path)
    except (OSError, json.JSONDecodeError) as e:
        return 2, (f"dstpu-telemetry --compare: cannot read current run "
                   f"{args.path}: {e}")
    if not current:
        return 2, (f"dstpu-telemetry --compare: no comparable metrics in "
                   f"{args.path} (need a bench JSON or a telemetry dir "
                   f"with engine/train_batch spans)")
    history = load_history(args.compare,
                           args.compare_pattern or DEFAULT_PATTERN,
                           exclude=args.path)
    report = compare_runs(current, history,
                          threshold=args.compare_threshold)
    report["current_run"] = args.path
    if args.as_json:
        text = json.dumps(report, indent=2, sort_keys=True, default=str)
    else:
        text = format_compare(report, history_dir=args.compare)
    if report["verdict"] == VERDICT_REGRESSION:
        return 3, text
    if report["verdict"] == VERDICT_NO_HISTORY:
        return 2, text
    return 0, text


if __name__ == "__main__":
    import sys

    sys.exit(main())
