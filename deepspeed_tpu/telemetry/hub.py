"""Telemetry hub: one object bundling tracer + metrics + events + memory.

The engine builds a :class:`Telemetry` from ``config.telemetry`` and installs
it process-globally (``set_telemetry``) so module-level instrumentation sites
— the comm facade, the monitor fan-out, fault counters, the checkpoint
engine — can reach it without threading a handle through every call chain.
``get_telemetry()`` returning ``None`` IS the disabled fast path: every site
guards with one attribute load + ``is None``.

Outputs (all under ``output_dir``):
  * ``events.jsonl``  — structured events, written through as they happen;
    spans and metric snapshots are appended at ``flush()``;
  * ``trace.json``    — Chrome-trace/Perfetto view of the recorded spans;
  * ``metrics.prom``  — Prometheus text-exposition snapshot.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from .events import EventLog
from .memory import MemorySampler
from .metrics import MetricsRegistry
from .trace import NULL_SPAN, Tracer

EVENTS_FILE = "events.jsonl"
TRACE_FILE = "trace.json"
PROM_FILE = "metrics.prom"


class Telemetry:
    def __init__(self, output_dir: str = "telemetry", jsonl: bool = True,
                 chrome_trace: bool = True, prometheus: bool = True,
                 fence: bool = False, memory_interval: int = 1,
                 max_spans: int = 100_000, histogram_max_samples: int = 4096,
                 jax_annotations: bool = True, events_max_mb: float = 0.0,
                 events_keep: int = 3):
        self.output_dir = os.path.abspath(output_dir)
        self.chrome_trace = bool(chrome_trace)
        self.prometheus = bool(prometheus)
        #: fence spans with block_until_ready on the value handed to span(sync=)
        self.fence = bool(fence)
        self.tracer = Tracer(enabled=True, max_spans=max_spans,
                             jax_annotations=jax_annotations)
        self.metrics = MetricsRegistry(
            histogram_max_samples=histogram_max_samples)
        self.events = EventLog(
            path=os.path.join(self.output_dir, EVENTS_FILE) if jsonl else None,
            max_bytes=int(float(events_max_mb) * 1024 * 1024),
            keep=events_keep)
        self.memory = MemorySampler(self.metrics, self.events,
                                    interval=memory_interval)
        self._flush_lock = threading.Lock()
        self._spans_flushed = 0
        self._closed = False
        # Run delimiter: events.jsonl is append-mode, so re-using an
        # output_dir accumulates runs — this marker lets the summarizer
        # isolate the latest run (matching trace.json, which is overwritten).
        self.events.emit("run_start", pid=os.getpid(),
                         output_dir=self.output_dir)

    @classmethod
    def from_config(cls, tcfg) -> "Telemetry":
        """Build from a ``TelemetryConfig`` block (runtime/config.py)."""
        return cls(
            output_dir=tcfg.output_dir,
            jsonl=tcfg.jsonl,
            chrome_trace=tcfg.chrome_trace,
            prometheus=tcfg.prometheus,
            fence=tcfg.fence,
            memory_interval=tcfg.memory_interval,
            max_spans=tcfg.max_spans,
            histogram_max_samples=tcfg.histogram_max_samples,
            jax_annotations=tcfg.jax_annotations,
            events_max_mb=getattr(tcfg, "events_max_mb", 0.0),
            events_keep=getattr(tcfg, "events_keep", 3),
        )

    # ---------------------------------------------------------------- #
    # Convenience instrumentation entry points
    # ---------------------------------------------------------------- #
    def span(self, name: str, sync: Any = None, **attrs):
        return self.tracer.span(name, sync=sync if self.fence else None,
                                **attrs)

    def event(self, kind: str, **fields) -> None:
        self.events.emit(kind, **fields)

    def record_comm_op(self, op_name: str, size_bytes: int,
                       duration_s: Optional[float], n_ranks: int,
                       algbw_gbps: float, busbw_gbps: float) -> None:
        """Per-collective aggregation: message sizes, latency, and bandwidth
        estimates, labelled by op (upgraded ``comms_logging`` path).

        ``duration_s=None`` marks a trace-time (in-jit) record: message size
        and call count are real, but there is no transfer to time — those
        land in ``comm/traced_calls`` and stay out of the latency/bandwidth
        histograms."""
        m = self.metrics
        m.counter("comm/calls").inc(op=op_name)
        m.histogram("comm/bytes").observe(size_bytes, op=op_name)
        if duration_s is None:
            m.counter("comm/traced_calls").inc(op=op_name)
        else:
            m.histogram("comm/latency_s").observe(duration_s, op=op_name)
            if algbw_gbps > 0:
                m.histogram("comm/algbw_gbps").observe(algbw_gbps, op=op_name)
            if busbw_gbps > 0:
                m.histogram("comm/busbw_gbps").observe(busbw_gbps, op=op_name)
        m.gauge("comm/ranks").set(n_ranks, op=op_name)

    def record_monitor_events(self, event_list) -> None:
        """Mirror monitor scalar events (label, value, step) into telemetry
        so TB/W&B/CSV writers and telemetry can never drift apart: gauges
        hold last/min/max per label, and one compact ``scalars`` JSONL event
        per batch keeps the full per-step history recoverable even with
        every writer disabled."""
        values = {}
        last_step = None
        for label, value, step in event_list:
            try:
                value = float(value)
                # a label colliding with a non-gauge metric name raises
                # TypeError — skip that scalar, never break the fan-out
                self.metrics.gauge(str(label)).set(value)
            except (TypeError, ValueError):
                continue
            values[str(label)] = value
            last_step = step
        if values:
            try:
                self.metrics.gauge("monitor/last_step").set(float(last_step))
            except (TypeError, ValueError):
                pass
            self.events.emit("scalars", step=last_step, values=values)

    # ---------------------------------------------------------------- #
    def flush(self) -> Dict[str, str]:
        """Write every export: new spans + a metric snapshot into the JSONL,
        the Chrome trace, and the Prometheus snapshot.  Idempotent and safe
        to call mid-run.  Returns {artifact: path}."""
        out: Dict[str, str] = {}
        with self._flush_lock:
            # _spans_flushed counts against the tracer's MONOTONIC total, not
            # the ring buffer length — ring eviction must not re-export old
            # spans or silently skip new ones.
            records, total = self.tracer.snapshot()
            unseen = total - self._spans_flushed
            missed = max(unseen - len(records), 0)
            if missed:   # evicted before this flush could export them
                self.events.emit("spans_dropped", count=missed,
                                 ring_capacity=self.tracer.max_spans)
            for rec in records[len(records) - min(unseen, len(records)):]:
                self.events.emit("span", **rec.to_dict())
            self._spans_flushed = total
            for row in self.metrics.snapshot():
                self.events.emit("metric", **row)
            self.events.flush()
            if self.events.path:
                out["events"] = self.events.path
            if self.chrome_trace:
                out["trace"] = self.tracer.export_chrome_trace(
                    os.path.join(self.output_dir, TRACE_FILE))
            if self.prometheus:
                from ..runtime.fault.atomic import atomic_write_text

                os.makedirs(self.output_dir, exist_ok=True)
                prom = os.path.join(self.output_dir, PROM_FILE)
                atomic_write_text(prom, self.metrics.prometheus_text())
                out["prometheus"] = prom
        return out

    def close(self) -> Dict[str, str]:
        if self._closed:
            return {}
        out = self.flush()
        self.events.close()
        self._closed = True
        return out


# --------------------------------------------------------------------- #
# Process-global instance
# --------------------------------------------------------------------- #
_GLOBAL: Optional[Telemetry] = None
_GLOBAL_LOCK = threading.Lock()


def set_telemetry(tel: Optional[Telemetry]) -> Optional[Telemetry]:
    """Install (or clear, with None) the process-global telemetry hub."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        previous, _GLOBAL = _GLOBAL, tel
    return previous


def get_telemetry() -> Optional[Telemetry]:
    return _GLOBAL


def telemetry_enabled() -> bool:
    return _GLOBAL is not None


def span(name: str, sync: Any = None, **attrs):
    """Module-level span against the global hub; NULL_SPAN when disabled."""
    tel = _GLOBAL
    if tel is None:
        return NULL_SPAN
    return tel.span(name, sync=sync, **attrs)


def emit_event(kind: str, **fields) -> None:
    """Fire-and-forget structured event against the global hub."""
    tel = _GLOBAL
    if tel is not None:
        tel.event(kind, **fields)
