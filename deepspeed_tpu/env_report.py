"""Environment report (reference: deepspeed/env_report.py, the ``ds_report``
CLI): framework versions, device inventory, op/kernel availability."""
from __future__ import annotations

import importlib
import shutil
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_FAIL = "\033[91m[FAIL]\033[0m"
YELLOW_NO = "\033[93m[NO]\033[0m"


def _try_version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return ""


def op_report() -> list:
    """Kernel/op availability (reference op compatibility table)."""
    rows = []
    try:
        import jax

        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    rows.append(("pallas flash attention", True, on_tpu))
    rows.append(("pallas fused adam/lion", True, on_tpu))
    rows.append(("pallas int8/int4 quantizer", True, on_tpu))
    try:
        from .ops.aio import aio_available

        rows.append(("native async-io (C++)", aio_available(), True))
    except Exception:
        rows.append(("native async-io (C++)", False, False))
    return rows


def main(hide_operator_status: bool = False, hide_errors_and_warnings: bool = False):
    import deepspeed_tpu

    lines = []
    lines.append("-" * 70)
    lines.append("DeepSpeed-TPU C++/Pallas op report")
    lines.append("-" * 70)
    if not hide_operator_status:
        for name, installed, compatible in op_report():
            status = GREEN_OK if installed else RED_FAIL
            compat = GREEN_OK if compatible else YELLOW_NO
            lines.append(f"{name:.<40} installed {status} compatible {compat}")
    lines.append("-" * 70)
    lines.append("General environment:")
    lines.append(f"deepspeed_tpu version ......... {deepspeed_tpu.__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        v = _try_version(mod)
        lines.append(f"{mod:.<30} {v or 'not installed'}")
    lines.append(f"python version ................ {sys.version.split()[0]}")
    lines.append(f"g++ ........................... "
                 f"{'found: ' + shutil.which('g++') if shutil.which('g++') else 'missing'}")
    try:
        import jax

        devs = jax.devices()
        lines.append(f"devices ....................... {[str(d) for d in devs]}")
        lines.append(f"default backend ............... {jax.default_backend()}")
    except Exception as e:
        if not hide_errors_and_warnings:
            lines.append(f"device probe failed: {e}")
    report = "\n".join(lines)
    print(report)
    return report


if __name__ == "__main__":
    main()
