"""Monitoring fan-out (reference: deepspeed/monitor/monitor.py:30).

``MonitorMaster`` dispatches scalar events to every enabled writer
(TensorBoard / W&B / CSV).  Writers degrade gracefully when their backing
library is absent (this image has no tensorboard/wandb — CSV always works).
Event tuples: ``(label, value, step)``.
"""
from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list: List[Event]) -> None:  # pragma: no cover
        raise NotImplementedError

    def flush(self) -> None:
        """Push buffered events to the backing store (engine shutdown hook)."""


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            path = os.path.join(config.output_path or "runs", config.job_name)
            self.summary_writer = SummaryWriter(log_dir=path)
        except Exception as e:
            logger.warning(f"tensorboard writer unavailable: {e}")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for label, value, step in event_list:
            self.summary_writer.add_scalar(label, value, step)
        self.summary_writer.flush()

    def flush(self) -> None:
        if self.summary_writer is not None:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        if not self.enabled:
            return
        try:
            import wandb

            wandb.init(team=config.team, project=config.project, group=config.group)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb unavailable: {e}")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for label, value, step in event_list:
            self._wandb.log({label: value}, step=step)


class CometMonitor(Monitor):
    """Reference: monitor/comet.py — gated on comet_ml availability."""

    def __init__(self, config):
        super().__init__(config)
        if not self.enabled:
            return
        try:
            import comet_ml

            self._exp = comet_ml.Experiment(project_name=getattr(config, "project", None))
        except Exception as e:
            logger.warning(f"comet unavailable: {e}")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for label, value, step in event_list:
            self._exp.log_metric(label, value, step=step)


class csvMonitor(Monitor):  # reference class name
    """CSV writer: one file open per label per ``write_events`` call instead
    of per event.  Default ``flush_every=1`` keeps write-through durability —
    every call lands on disk, so a crash loses nothing.  Raising it buffers
    rows across calls (fewer opens on slow/remote filesystems) at the cost of
    up to ``flush_every - 1`` tail rows on a crash; the engine flushes on
    shutdown either way."""

    def __init__(self, config, flush_every: Optional[int] = None):
        super().__init__(config)
        self.filenames = {}
        if flush_every is None:  # config block `csv_monitor.flush_every`
            flush_every = getattr(config, "flush_every", 1) or 1
        self.flush_every = max(int(flush_every), 1)
        self._buffer: dict = {}   # label -> [(step, value), ...]
        self._buffered = 0
        if self.enabled:
            self.output_path = os.path.join(config.output_path or "csv_logs",
                                            config.job_name)
            os.makedirs(self.output_path, exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for label, value, step in event_list:
            self._buffer.setdefault(label, []).append((step, value))
            self._buffered += 1
        if self._buffered >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        if not self.enabled or not self._buffered:
            return
        for label, rows in self._buffer.items():
            if not rows:
                continue
            fname = os.path.join(self.output_path,
                                 label.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", label])
                w.writerows(rows)
        self._buffer.clear()
        self._buffered = 0


def fault_events(step: int) -> List[Event]:
    """Fault-subsystem counters (``Fault/retries``, ``Fault/watchdog_timeouts``,
    ``Fault/injected/*`` …) as monitor events.  Retries that silently succeed
    are still a storage-health signal worth graphing — a run whose retry curve
    climbs is about to become a run that fails."""
    from ..runtime.fault.retry import fault_counters

    return [(f"Fault/{label}", float(value), step)
            for label, value in sorted(fault_counters().items())]


class MonitorMaster(Monitor):
    def __init__(self, ds_config):
        from ..runtime.config import MonitorWriterConfig

        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = csvMonitor(ds_config.csv_monitor)
        self.comet_monitor = CometMonitor(
            getattr(ds_config, "comet", None) or MonitorWriterConfig())
        self._writers = (self.tb_monitor, self.wandb_monitor, self.csv_monitor,
                         self.comet_monitor)
        self.enabled = any(m.enabled for m in self._writers)

    def write_events(self, event_list: List[Event]) -> None:
        """Fan events out to every enabled writer AND the telemetry metrics
        registry.  The registry route is unconditional (when a telemetry hub
        is installed) so scalar history exists even with every writer
        disabled, and writers vs. telemetry can never drift apart — both see
        the exact same event tuples."""
        from ..telemetry import get_telemetry
        from ..utils.logging import warning_once

        tel = get_telemetry()
        if tel is not None:
            try:
                tel.record_monitor_events(event_list)
            except Exception as e:  # observability must never kill a step
                warning_once(f"telemetry monitor route failed: {e!r}")
        for m in self._writers:
            if m.enabled:
                m.write_events(event_list)

    def flush(self) -> None:
        for m in self._writers:
            if m.enabled:
                m.flush()
