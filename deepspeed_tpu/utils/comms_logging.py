"""Communication operation logging (reference: deepspeed/utils/comms_logging.py:67).

Collectives under ``jit`` are compiled, so per-call device latency is not
observable from Python the way CUDA events make it on GPU.  We therefore log
what IS knowable and useful on TPU:

  * trace-time records: op name, message size, mesh axes, dtype — every time a
    facade collective is *traced* (i.e., per compiled program, not per step);
  * wall-clock records for host-blocking ops (barrier, multihost broadcast);
  * algorithmic/bus bandwidth estimates from message size and link count,
    reported by ``log_summary`` like the reference.

Enable via config ``comms_logger`` (see comm/config.py) or
``comm.configure(enabled=True)``.
"""
from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional


def get_caller_func(frame_depth: int = 3) -> str:
    """Name of the function ``frame_depth`` frames up the stack.

    Robust to shallow stacks: a fixed ``sys._getframe(3)`` raises ValueError
    when the caller sits near the top level (REPL, script body, test
    function) — walk up instead and stop at the outermost frame.
    """
    import sys

    frame = sys._getframe(0)
    for _ in range(max(int(frame_depth), 0)):
        if frame.f_back is None:
            break
        frame = frame.f_back
    return frame.f_code.co_name


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n_ranks: int):
    """Algorithmic and bus bandwidth in GB/s (mirrors reference formulas)."""
    duration_s = max(duration_s, 1e-9)
    n = max(n_ranks, 1)
    if comm_op in ("all_to_all", "all_to_all_single"):
        # Each rank sends (n-1)/n of its buffer.
        algbw = size_bytes / duration_s
        busbw = algbw * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        algbw = size_bytes / duration_s
        busbw = algbw * ((n - 1) / n)
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        algbw = size_bytes / duration_s
        busbw = algbw * (2 * (n - 1) / n)
    else:  # send/recv/broadcast/ppermute
        algbw = size_bytes / duration_s
        busbw = algbw
    return algbw / 1e9, busbw / 1e9


def record_comm_telemetry(op_name: str, size_bytes: int, duration_s: float,
                          n_ranks: int, algbw: Optional[float] = None,
                          busbw: Optional[float] = None,
                          trace_time: bool = False) -> None:
    """Aggregate one collective into the telemetry metrics registry (no-op
    when telemetry is disabled): per-op message-size/latency/bandwidth
    histograms the run summary renders into the comm table.

    ``trace_time=True`` marks an in-jit invocation: the wall time measured
    around a *trace* is compile-time bookkeeping, not a transfer, so only
    calls/sizes/ranks are aggregated — one bogus trace sample would corrupt
    the mean bandwidth the summary table reports."""
    from ..telemetry import get_telemetry

    tel = get_telemetry()
    if tel is None:
        return
    if trace_time:
        tel.record_comm_op(op_name, size_bytes, None, n_ranks, 0.0, 0.0)
        return
    if algbw is None or busbw is None:
        algbw, busbw = calc_bw_log(op_name, size_bytes, duration_s, n_ranks)
    tel.record_comm_op(op_name, size_bytes, duration_s, n_ranks, algbw, busbw)


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops: Optional[List[str]] = None,
                 debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # op name -> size -> [count, total_latency_s, algbw_sum, busbw_sum]
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(dict)

    def configure(self, enabled=None, verbose=None, prof_all=None, prof_ops=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops

    def should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, raw_name: str, size_bytes: int,
               duration_s: float, n_ranks: int,
               trace_time: bool = False) -> None:
        if trace_time:
            # the documented "zero latency marker": a jit trace is not a
            # transfer, so its wall time (compile bookkeeping) must not skew
            # the per-size latency/bandwidth aggregates log_summary reports
            duration_s, algbw, busbw = 0.0, 0.0, 0.0
        else:
            algbw, busbw = calc_bw_log(op_name, size_bytes, duration_s, n_ranks)
        per_size = self.comms_dict[op_name].setdefault(size_bytes, [0, 0.0, 0.0, 0.0])
        per_size[0] += 1
        per_size[1] += duration_s
        per_size[2] += algbw
        per_size[3] += busbw
        record_comm_telemetry(op_name, size_bytes, duration_s, n_ranks,
                              algbw, busbw, trace_time=trace_time)
        if self.verbose:
            from .logging import logger

            logger.info(
                f"comm op: {op_name} ({raw_name}) | size: {size_bytes} B | "
                f"time: {duration_s*1e3:.3f} ms | algbw: {algbw:.2f} GB/s | busbw: {busbw:.2f} GB/s")

    def log_summary(self, show_straggler: bool = False) -> str:
        """Render the per-op/per-size summary table (reference: comm/comm.py:428)."""
        lines = []
        header = f"{'Comm. Op':<22}{'Message Size':>14}{'Count':>8}{'Total Lat(ms)':>15}{'Avg Lat(ms)':>13}{'algbw(GB/s)':>13}{'busbw(GB/s)':>13}"
        lines.append(header)
        for op_name, sizes in sorted(self.comms_dict.items()):
            lines.append(op_name)
            for size, (count, lat, algbw, busbw) in sorted(sizes.items()):
                count = int(count)
                avg_lat = lat / count * 1e3 if count else 0.0
                lines.append(
                    f"{'':<22}{_fmt_size(size):>14}{count:>8}{lat*1e3:>15.2f}{avg_lat:>13.2f}"
                    f"{algbw / max(count,1):>13.2f}{busbw / max(count,1):>13.2f}")
        out = "\n".join(lines)
        from .logging import logger

        logger.info("\n" + out)
        return out


def _fmt_size(num_bytes: int) -> str:
    if num_bytes == 0:
        return "0 B"
    units = ["B", "KB", "MB", "GB", "TB"]
    k = min(int(math.log(num_bytes, 1024)), len(units) - 1)
    return f"{num_bytes / 1024**k:.2f} {units[k]}"
