from .comms_logging import CommsLogger
from .logging import log_dist, logger, warning_once

__all__ = ["CommsLogger", "log_dist", "logger", "warning_once"]
