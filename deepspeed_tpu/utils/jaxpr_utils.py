"""Jaxpr introspection helpers shared by compiled-program perf gates
(tests), bench modes, and the performance-attribution profiler —
structural facts about a traced program: ``lax.scan`` trip counts (the
pipeline tick loops' bubble evidence) and per-``jax.named_scope``
flop/byte attribution (the profiler's module cost tree).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Tuple

import jax
import numpy as np


#: primitives whose sub-jaxpr is a scalar COMBINER (e.g. scatter-add's
#: `{lambda a,b. add a b}`), not program structure — recursing into it would
#: count one combiner application instead of eqn_flops's per-element figure
_COMBINER_PRIMS_PREFIXES = ("scatter", "reduce", "select_and_scatter",
                            "select_and_gather", "argmin", "argmax",
                            "cumsum", "cumprod", "cummax", "cummin")


def _is_leaf_eqn(eqn) -> bool:
    return eqn.primitive.name.startswith(_COMBINER_PRIMS_PREFIXES)


def _sub_jaxprs(eqn):
    """Every sub-jaxpr hiding in an eqn's params (pjit/scan/cond/while/
    remat/custom_* all stash theirs under different keys)."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for inner in vals:
            while hasattr(inner, "jaxpr"):      # ClosedJaxpr → Jaxpr
                inner = inner.jaxpr
            if hasattr(inner, "eqns"):
                yield inner


def scan_lengths(fn, *args) -> List[int]:
    """All ``lax.scan`` static trip counts in ``fn``'s jaxpr, including
    scans nested inside pjit/cond/while/other-scan sub-jaxprs."""
    found: List[int] = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                found.append(int(eqn.params["length"]))
            for inner in _sub_jaxprs(eqn):
                walk(inner)

    walk(jax.make_jaxpr(fn)(*args).jaxpr)
    return found


# --------------------------------------------------------------------- #
# Per-named-scope cost attribution
# --------------------------------------------------------------------- #
#: primitives whose flop count is the *output* element count and which the
#: hardware evaluates via its transcendental unit (tracked separately, like
#: XLA cost analysis does)
_TRANSCENDENTAL = frozenset({
    "exp", "log", "log1p", "expm1", "tanh", "logistic", "erf", "erfc",
    "erf_inv", "sin", "cos", "tan", "atan2", "pow", "rsqrt", "sqrt",
    "cbrt", "digamma", "lgamma",
})

#: elementwise / reduction primitives counted as one flop per element
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "sign",
    "floor", "ceil", "round", "rem", "nextafter", "add_any", "and", "or",
    "xor", "not", "select_n", "clamp", "integer_pow", "square",
})

_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cumprod", "cummax", "cummin",
})

#: pure data-movement: zero flops, bytes only
_ZERO_FLOP = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "gather",
    "scatter", "rev", "pad", "convert_element_type",
    "bitcast_convert_type", "copy", "iota", "stop_gradient", "device_put",
    "split", "expand_dims",
})

#: combining scatters: one combine op per UPDATES element (the embedding
#: gradient lowers to scatter-add of ~B·S·D adds — not data movement)
_SCATTER_COMBINE = frozenset({"scatter-add", "scatter-mul", "scatter-max",
                              "scatter-min"})


def _aval_size(v) -> int:
    try:
        return int(np.prod(v.aval.shape))
    except Exception:  # noqa: BLE001 — abstract tokens etc.
        return 0


def _aval_bytes(v) -> int:
    try:
        aval = v.aval
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:  # noqa: BLE001
        return 0


def _dot_general_flops(eqn) -> float:
    """2·batch·M·N·K for a ``dot_general`` from its dimension numbers."""
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval.shape, eqn.invars[1].aval.shape
    batch = float(np.prod([lhs[i] for i in lb], initial=1.0))
    contract = float(np.prod([lhs[i] for i in lc], initial=1.0))
    m = float(np.prod([d for i, d in enumerate(lhs)
                       if i not in lc and i not in lb], initial=1.0))
    n = float(np.prod([d for i, d in enumerate(rhs)
                       if i not in rc and i not in _rb_set(eqn)], initial=1.0))
    return 2.0 * batch * m * n * contract


def _rb_set(eqn):
    return set(eqn.params["dimension_numbers"][1][1])


def eqn_flops(eqn) -> Tuple[float, float]:
    """(flops, transcendentals) analytic estimate for one jaxpr eqn.

    Matmuls get the exact 2·M·N·K count; elementwise/reduction ops count one
    flop per element; data movement counts zero.  Unknown primitives fall
    back to output element count — an undercount for exotic kernels, never
    an overcount that would inflate MFU.
    """
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn), 0.0
    if name in _SCATTER_COMBINE:
        # invars: operand, indices, updates — one combine per update element
        updates = eqn.invars[-1] if eqn.invars else None
        return (float(_aval_size(updates)) if updates is not None else 0.0,
                0.0)
    out_elems = float(sum(_aval_size(v) for v in eqn.outvars))
    if name in _TRANSCENDENTAL:
        return out_elems, out_elems
    if name in _ZERO_FLOP:
        return 0.0, 0.0
    if name in _REDUCTIONS:
        return float(sum(_aval_size(v) for v in eqn.invars)), 0.0
    if name in _ELEMENTWISE:
        return out_elems, 0.0
    return out_elems, 0.0


def eqn_bytes(eqn) -> float:
    """Static bytes-touched estimate: operand + result footprints.  Ignores
    fusion (XLA will elide many intermediates), so per-module arithmetic
    intensity from this is a lower bound."""
    return float(sum(_aval_bytes(v) for v in eqn.invars) +
                 sum(_aval_bytes(v) for v in eqn.outvars))


_WRAPPER = re.compile(r"^(transpose|jvp|vmap|pmap)\((.*)\)$")


def _normalize_component(comp: str) -> Tuple[str, bool]:
    """Strip AD/batching decorations from one name-stack element.

    ``transpose(jvp(layers))`` → (``layers``, True): the transpose wrapper
    marks backward-pass eqns.  ``rematted_computation`` (the recompute body
    jax.checkpoint splices in) is dropped from the path but noted.
    """
    bwd = False
    while True:
        m = _WRAPPER.match(comp)
        if m is None:
            break
        if m.group(1) == "transpose":
            bwd = True
        comp = m.group(2)
    return comp, bwd


def _split_scope(stack_str: str) -> Tuple[Tuple[str, ...], str]:
    """Name-stack string → (normalized scope path, phase).

    Phase: ``bwd`` when any component carries a transpose() wrapper,
    ``remat`` when the path runs through a rematted_computation body
    (recompute work — real flops, but double-counted against fwd), else
    ``fwd``.
    """
    comps: List[str] = []
    bwd = remat = False
    for raw in stack_str.split("/"):
        if not raw:
            continue
        comp, is_bwd = _normalize_component(raw)
        bwd = bwd or is_bwd
        if comp == "rematted_computation":
            remat = True
            continue
        if comp:
            comps.append(comp)
    phase = "bwd" if bwd else ("remat" if remat else "fwd")
    return tuple(comps), phase


@dataclasses.dataclass
class ScopeCost:
    """Accumulated static cost of every eqn under one named-scope path."""

    flops: float = 0.0
    bytes: float = 0.0
    transcendentals: float = 0.0
    eqns: int = 0
    flops_by_phase: Dict[str, float] = dataclasses.field(default_factory=dict)

    def add(self, flops: float, byts: float, trans: float, phase: str,
            count: int = 1) -> None:
        self.flops += flops
        self.bytes += byts
        self.transcendentals += trans
        self.eqns += count
        self.flops_by_phase[phase] = self.flops_by_phase.get(phase, 0.0) + flops


def scope_costs_of_jaxpr(jaxpr) -> Dict[Tuple[str, ...], ScopeCost]:
    """:func:`scope_costs` on an already-traced jaxpr — callers that traced
    once for a flop total can reuse the jaxpr instead of re-tracing (a full
    fwd+bwd+optimizer trace costs seconds on large models)."""
    costs: Dict[Tuple[str, ...], ScopeCost] = {}

    def walk(jx, prefix: Tuple[str, ...], mult: float) -> None:
        for eqn in jx.eqns:
            comps, phase = _split_scope(str(eqn.source_info.name_stack))
            scope = prefix + comps
            subs = [] if _is_leaf_eqn(eqn) else list(_sub_jaxprs(eqn))
            if subs:
                inner_mult = mult
                if eqn.primitive.name == "scan":
                    inner_mult *= float(eqn.params.get("length", 1))
                if eqn.primitive.name == "cond":
                    # count only the most expensive branch, not their sum
                    best, best_cost = None, -1.0
                    for sub in subs:
                        c = _jaxpr_flops(sub)
                        if c > best_cost:
                            best, best_cost = sub, c
                    subs = [best] if best is not None else []
                for sub in subs:
                    walk(sub, scope, inner_mult)
                continue
            flops, trans = eqn_flops(eqn)
            byts = eqn_bytes(eqn)
            costs.setdefault(scope, ScopeCost()).add(
                flops * mult, byts * mult, trans * mult, phase)

    walk(jaxpr, (), 1.0)
    return costs


def scope_costs(fn, *args) -> Dict[Tuple[str, ...], ScopeCost]:
    """Attribute ``fn``'s analytic flops/bytes to ``jax.named_scope`` paths.

    Traces ``fn`` (no compile) and walks the jaxpr, recursing into
    pjit/scan/cond/while/remat sub-jaxprs.  Scan bodies multiply by the
    static trip count; cond takes the most expensive branch; while bodies
    count one trip (the count is dynamic — an explicit undercount).
    AD decorations are stripped so forward and backward eqns of the same
    module aggregate under one path (split out in ``flops_by_phase``).

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct``s.
    """
    return scope_costs_of_jaxpr(jax.make_jaxpr(fn)(*args).jaxpr)


def _jaxpr_flops(jx) -> float:
    total = 0.0
    for eqn in jx.eqns:
        subs = [] if _is_leaf_eqn(eqn) else list(_sub_jaxprs(eqn))
        if subs:
            if eqn.primitive.name == "scan":
                total += float(eqn.params.get("length", 1)) * \
                    sum(_jaxpr_flops(s) for s in subs)
            elif eqn.primitive.name == "cond":
                # most expensive branch only — matching scope_costs_of_jaxpr
                # so the module tree and the MFU numerator agree
                total += max((_jaxpr_flops(s) for s in subs), default=0.0)
            else:
                total += sum(_jaxpr_flops(s) for s in subs)
        else:
            total += eqn_flops(eqn)[0]
    return total


def total_flops_of_jaxpr(jaxpr) -> float:
    """:func:`total_flops` on an already-traced jaxpr."""
    return _jaxpr_flops(jaxpr)


def total_flops(fn, *args) -> float:
    """Whole-program analytic flop count (trace-only — no XLA compile).
    Cheaper than ``compiled.cost_analysis()`` and fusion-independent; use it
    when an extra compile is unaffordable and a matmul-exact/elementwise-
    approximate count is enough."""
    return _jaxpr_flops(jax.make_jaxpr(fn)(*args).jaxpr)
