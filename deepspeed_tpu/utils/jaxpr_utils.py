"""Jaxpr introspection helpers shared by compiled-program perf gates
(tests) and bench modes — structural facts about a traced program, e.g.
every ``lax.scan`` trip count (the pipeline tick loops' bubble evidence).
"""
from __future__ import annotations

from typing import List

import jax


def scan_lengths(fn, *args) -> List[int]:
    """All ``lax.scan`` static trip counts in ``fn``'s jaxpr, including
    scans nested inside pjit/cond/while/other-scan sub-jaxprs."""
    found: List[int] = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                found.append(int(eqn.params["length"]))
            for v in eqn.params.values():
                inner = v
                while hasattr(inner, "jaxpr"):      # ClosedJaxpr → Jaxpr
                    inner = inner.jaxpr
                if hasattr(inner, "eqns"):
                    walk(inner)

    walk(jax.make_jaxpr(fn)(*args).jaxpr)
    return found
