"""Rank-filtered logging (reference analogue: deepspeed/utils/logging.py)."""
from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        lg.addHandler(handler)
    env_level = os.environ.get("DSTPU_LOG_LEVEL")
    if env_level:
        lg.setLevel(LOG_LEVELS.get(env_level.lower(), logging.INFO))
    return lg


logger = _create_logger()


def _process_rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None,
             level: int = logging.INFO) -> None:
    """Log only on the given process ranks (default: rank 0)."""
    my_rank = _process_rank()
    ranks = list(ranks) if ranks is not None else [0]
    if my_rank in ranks or -1 in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
