"""Wall-clock + throughput timers (reference: deepspeed/utils/timer.py:44,199).

CUDA-event timing maps to ``jax.block_until_ready`` fences; under jit the
per-phase breakdown (fwd/bwd/step) is only meaningful for the imperative API —
the fused train step reports whole-step time.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


class _Timer:
    def __init__(self, name: str, telemetry=None):
        self.name = name
        self.started = False
        self._start = 0.0
        self.elapsed_total = 0.0
        self.count = 0
        self.telemetry = telemetry

    def start(self):
        self.started = True
        self._start = time.perf_counter()

    def stop(self, sync=None, reset=False):
        if not self.started:
            return
        if sync is not None:
            import jax

            jax.block_until_ready(sync)
        duration = time.perf_counter() - self._start
        self.elapsed_total += duration
        self.count += 1
        self.started = False
        if self.telemetry is not None:
            self.telemetry.metrics.histogram("timer/seconds").observe(
                duration, name=self.name)

    def elapsed(self, reset: bool = True) -> float:
        out = self.elapsed_total
        if reset:
            self.reset()
        return out

    def mean(self) -> float:
        return self.elapsed_total / max(self.count, 1)

    def reset(self):
        self.elapsed_total = 0.0
        self.count = 0
        self.started = False


class SynchronizedWallClockTimer:
    """Named-timer registry (reference: utils/timer.py:44).  With a telemetry
    hub attached, every ``stop()`` also lands in the ``timer/seconds``
    histogram (labelled by timer name)."""

    def __init__(self, telemetry=None):
        self.timers: Dict[str, _Timer] = {}
        self.telemetry = telemetry

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, telemetry=self.telemetry)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False) -> str:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        msg = "time (ms) | " + " | ".join(parts)
        from .logging import log_dist

        log_dist(msg, ranks=[0])
        return msg


class ThroughputTimer:
    """Samples/sec + TFLOPS estimate (reference: utils/timer.py:199)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False,
                 logging_fn=None, telemetry=None):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.logging = logging_fn
        self.telemetry = telemetry
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        #: wall time of the most recent post-warmup step (straggler
        #: detection + roofline gauges read this)
        self.last_step_time = 0.0
        self._start = 0.0
        self.started = False

    def start(self):
        self.started = True
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True, sync=None):
        if not self.started:
            return
        self.started = False
        if sync is not None:
            import jax

            jax.block_until_ready(sync)
        duration = time.perf_counter() - self._start
        if not global_step:
            return
        self.global_step_count += 1
        if self.global_step_count <= self.start_step:
            return  # skip warmup/compile steps
        self.last_step_time = duration
        self.total_elapsed_time += duration
        self.step_elapsed_time += duration
        if self.telemetry is not None:
            m = self.telemetry.metrics
            m.histogram("engine/step_time_s").observe(duration)
            m.gauge("engine/samples_per_sec").set(self.avg_samples_per_sec())
            m.counter("engine/steps").inc()
        if report_speed and self.logging and \
                self.global_step_count % self.steps_per_output == 0:
            self.logging(
                f"step={self.global_step_count} "
                f"samples/sec={self.avg_samples_per_sec():.2f} "
                f"ms/step={self.step_elapsed_time / self.steps_per_output * 1000:.1f}")
            self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        measured = self.global_step_count - self.start_step
        if measured <= 0 or self.total_elapsed_time == 0:
            return 0.0
        return self.batch_size / (self.total_elapsed_time / measured)
