"""Job launcher CLI (reference: deepspeed/launcher/runner.py:419 main(),
hostfile parsing :213, include/exclude filters :293; per-node launch.py:133).

TPU pods run ONE process per host (JAX owns all local chips), so the launcher
is simpler than the reference's one-proc-per-GPU model: parse a hostfile,
compute the coordinator address, and start the user script on every host with
``COORDINATOR_ADDRESS``/``DSTPU_RANK``/``DSTPU_WORLD_SIZE`` env — the env that
``comm.init_distributed`` consumes.  Single-host runs exec in-place.

Usage:  dstpu [--hostfile HF] [--include ...] [--master_port P] script.py args…
"""
from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS", "XLA_FLAGS"]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="host filter, e.g. 'worker-0@worker-1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="host exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "mpich", "slurm",
                                 "local", "popen"])
    parser.add_argument("--num_procs", type=int, default=2,
                        help="popen launcher: local process count (pod "
                             "rehearsal — one process per simulated host)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Reference :213 — '<hostname> slots=<n>' per line, '#' comments."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                host, slots = line.split()
                count = int(slots.split("=")[1])
            except (ValueError, IndexError):
                raise ValueError(f"malformed hostfile line: {line!r}")
            if host in resource_pool:
                raise ValueError(f"duplicate host {host!r} in hostfile")
            resource_pool[host] = count
    return resource_pool or None


def parse_inclusion_exclusion(resource_pool: Dict[str, int], inclusion: str,
                              exclusion: str) -> Dict[str, int]:
    """Reference :293 — 'host1@host2' selects hosts; 'host:0,1' selects slots
    (slot selection is not meaningful on TPU hosts — host-granular only)."""
    active = OrderedDict(resource_pool)
    if inclusion:
        wanted = set(h.split(":")[0] for h in inclusion.split("@"))
        unknown = wanted - set(active)
        if unknown:
            raise ValueError(f"included hosts not in hostfile: {sorted(unknown)}")
        active = OrderedDict((h, n) for h, n in active.items() if h in wanted)
    if exclusion:
        dropped = set(h.split(":")[0] for h in exclusion.split("@"))
        active = OrderedDict((h, n) for h, n in active.items() if h not in dropped)
    if not active:
        raise ValueError("no hosts remain after include/exclude filters")
    return active


def encode_world_info(resource_pool: Dict[str, int]) -> str:
    import base64
    import json

    return base64.urlsafe_b64encode(
        json.dumps(resource_pool).encode()).decode()


def build_launch_env(rank: int, world_size: int, master_addr: str,
                     master_port: int) -> Dict[str, str]:
    env = {k: os.environ[k] for k in EXPORT_ENVS if k in os.environ}
    env.update({
        "DSTPU_RANK": str(rank),
        "DSTPU_WORLD_SIZE": str(world_size),
        "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "RANK": str(rank),
        "WORLD_SIZE": str(world_size),
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
    })
    return env


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if args.launcher == "popen":
        # Localhost pod rehearsal (VERDICT r3 #10): N distinct processes +
        # a real jax.distributed coordinator on 127.0.0.1 — the same
        # per-rank env contract a physical pod launch uses, so a real
        # slice becomes a hostfile change, not new code.  One process per
        # simulated host (the TPU one-proc-per-host model).
        world_size = args.num_procs
        master_addr = args.master_addr or "127.0.0.1"
        procs: List[subprocess.Popen] = []
        for rank in range(world_size):
            # local children inherit the full env (same-host semantics);
            # build_launch_env supplies the per-rank rendezvous contract
            env = dict(os.environ)
            env.update(build_launch_env(rank, world_size, master_addr,
                                        args.master_port))
            cmd = [sys.executable, args.user_script] + args.user_args
            logger.info(f"rank {rank}: {' '.join(map(shlex.quote, cmd))}")
            procs.append(subprocess.Popen(cmd, env=env))
        # fail fast: one dead rank would leave the others blocked in a
        # collective until the distributed timeout — terminate peers on the
        # first nonzero exit (reference runner.py sigkill_handler semantics)
        rc = 0
        live = list(procs)
        while live:
            time.sleep(0.2)
            for p in list(live):
                ret = p.poll()
                if ret is None:
                    continue
                live.remove(p)
                if ret != 0 and rc == 0:
                    rc = ret
                    logger.error(f"a rank exited rc={ret}; "
                                 f"terminating {len(live)} peer(s)")
                    for q in live:
                        q.terminate()
        sys.exit(rc)

    if not resource_pool or args.launcher == "local":
        # single host: exec in place (reference single-node path :529)
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info(f"launching local: {' '.join(map(shlex.quote, cmd))}")
        result = subprocess.run(cmd)
        sys.exit(result.returncode)

    active = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    hosts = list(active)
    master_addr = args.master_addr or hosts[0]
    world_size = len(hosts)

    # rank-free shared env: workers derive rank from the backend's native
    # env (or DSTPU_NODE_LIST for pdsh/ssh) — see multinode_runner.py
    shared_env = build_launch_env(0, world_size, master_addr, args.master_port)
    for key in ("RANK", "DSTPU_RANK", "LOCAL_RANK"):
        shared_env.pop(key, None)

    from .multinode_runner import RUNNERS, MultiNodeRunner

    if args.launcher in RUNNERS:
        # single fan-out command (reference multinode_runner.py backends)
        runner = RUNNERS[args.launcher](args.user_script, args.user_args,
                                        shared_env)
        if not runner.backend_installed():
            logger.error(f"launcher backend {args.launcher!r} not installed")
            sys.exit(1)
        cmd = runner.get_cmd(hosts, master_addr, args.master_port)
        logger.info(f"launching via {args.launcher}: "
                    f"{' '.join(map(shlex.quote, cmd))}")
        env = dict(os.environ)
        env.update(runner.exports)      # slurm --export=ALL inherits these
        for key in ("RANK", "DSTPU_RANK", "LOCAL_RANK"):
            env.pop(key, None)          # stale launcher-env ranks would be
        sys.exit(subprocess.run(cmd, env=env).returncode)  # fanned to all tasks

    # ssh: one remote command per host, with the true per-rank env
    base = MultiNodeRunner(args.user_script, args.user_args, shared_env)
    base._set_rendezvous(master_addr, args.master_port)
    procs: List[subprocess.Popen] = []
    for rank, host in enumerate(hosts):
        remote_cmd = base.worker_cmdline(
            {"RANK": str(rank), "DSTPU_RANK": str(rank),
             "WORLD_SIZE": str(world_size),
             "DSTPU_WORLD_SIZE": str(world_size)})
        logger.info(f"rank {rank} @ {host}")
        procs.append(subprocess.Popen(["ssh", host, remote_cmd]))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
