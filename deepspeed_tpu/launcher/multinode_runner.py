"""Multinode launcher backends (reference: launcher/multinode_runner.py —
PDSHRunner :51, OpenMPIRunner :120, MPICHRunner :200, SlurmRunner :357).

Each runner builds ONE fan-out command that starts a worker per host; on TPU
pods each host runs one process (jax.distributed handles the in-host chips).
Rank is NOT baked into the exported env — a single fan-out command cannot
carry per-host values — so each worker derives its rank from the backend's
native env (OMPI_COMM_WORLD_RANK / PMI_RANK / SLURM_PROCID) or, for pdsh,
from its hostname's position in ``DSTPU_NODE_LIST``; ``comm.init_distributed``
implements that discovery order.
"""
from __future__ import annotations

import os
import shlex
import shutil
import sys
from typing import Dict, List, Sequence

#: env keys that must never be fanned out identically to every host
_RANK_KEYS = ("RANK", "DSTPU_RANK", "LOCAL_RANK")


class MultiNodeRunner:
    name = "base"

    def __init__(self, user_script: str, user_args: Sequence[str],
                 exports: Dict[str, str]):
        self.user_script = user_script
        self.user_args = list(user_args)
        self.exports = {k: v for k, v in exports.items()
                        if k not in _RANK_KEYS}

    def backend_installed(self) -> bool:
        raise NotImplementedError

    def _set_rendezvous(self, master_addr: str, master_port: int) -> None:
        self.exports.update({
            "MASTER_ADDR": master_addr,
            "MASTER_PORT": str(master_port),
            "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        })

    def get_cmd(self, hosts: List[str], master_addr: str,
                master_port: int) -> List[str]:
        raise NotImplementedError

    def worker_cmdline(self, extra_env: "Dict[str, str] | None" = None) -> str:
        """Shell line that cd's into the workdir, applies exports, and runs
        the user script (shared by pdsh and the ssh per-host path)."""
        env = dict(self.exports)
        env.update(extra_env or {})
        exports = " ".join(f"{k}={shlex.quote(str(v))}"
                           for k, v in env.items())
        return (f"cd {shlex.quote(os.getcwd())} && {exports} "
                f"{sys.executable} {shlex.quote(self.user_script)} "
                + " ".join(map(shlex.quote, self.user_args)))


class PDSHRunner(MultiNodeRunner):
    name = "pdsh"

    def backend_installed(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, hosts, master_addr, master_port):
        self._set_rendezvous(master_addr, master_port)
        # workers find their rank via hostname position in this list
        # (comm.init_distributed's DSTPU_NODE_LIST fallback)
        self.exports["DSTPU_NODE_LIST"] = ",".join(hosts)
        return ["pdsh", "-S", "-w", ",".join(hosts), self.worker_cmdline()]


class OpenMPIRunner(MultiNodeRunner):
    name = "openmpi"

    def backend_installed(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, hosts, master_addr, master_port):
        self._set_rendezvous(master_addr, master_port)
        cmd = ["mpirun", "-np", str(len(hosts)), "--host", ",".join(hosts),
               "--map-by", "ppr:1:node"]
        for k, v in self.exports.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + [sys.executable, self.user_script] + self.user_args


class MPICHRunner(MultiNodeRunner):
    name = "mpich"

    def backend_installed(self) -> bool:
        return shutil.which("mpiexec") is not None

    def get_cmd(self, hosts, master_addr, master_port):
        self._set_rendezvous(master_addr, master_port)
        cmd = ["mpiexec", "-n", str(len(hosts)), "-hosts", ",".join(hosts)]
        for k, v in self.exports.items():
            cmd += ["-genv", k, str(v)]
        return cmd + [sys.executable, self.user_script] + self.user_args


class SlurmRunner(MultiNodeRunner):
    name = "slurm"

    def backend_installed(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, hosts, master_addr, master_port):
        self._set_rendezvous(master_addr, master_port)
        # env values (XLA_FLAGS…) may contain commas/spaces that srun's
        # --export K=V parser mangles: rely on --export=ALL propagating the
        # parent process env instead (runner.py launches this command with
        # self.exports merged into the subprocess env).
        cmd = ["srun", "--ntasks", str(len(hosts)), "--ntasks-per-node", "1",
               "--export=ALL"]
        if hosts:
            cmd += ["--nodelist", ",".join(hosts)]
        return cmd + [sys.executable, self.user_script] + self.user_args


RUNNERS = {r.name: r for r in
           (PDSHRunner, OpenMPIRunner, MPICHRunner, SlurmRunner)}
