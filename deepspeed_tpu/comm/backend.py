"""Communication backend ABC (reference analogue: deepspeed/comm/backend.py:25).

On TPU there is exactly one real backend — XLA collectives over ICI/DCN — but
the ABC is kept so the comm facade, comms logger, and tests are backend-neutral
(the CPU-simulated mesh uses the same backend over the host platform).
"""
from __future__ import annotations

import abc


class Backend(abc.ABC):
    def __init__(self, name: str):
        self.name = name
        self.initialized = False

    def is_initialized(self) -> bool:
        return self.initialized

    @abc.abstractmethod
    def init_process_group(self, **kwargs) -> None:
        ...

    @abc.abstractmethod
    def get_rank(self) -> int:
        ...

    @abc.abstractmethod
    def get_world_size(self) -> int:
        ...

    @abc.abstractmethod
    def destroy_process_group(self) -> None:
        ...


class XlaBackend(Backend):
    """Multi-host process bootstrap via ``jax.distributed`` plus XLA collectives.

    Unlike the reference's ``TorchBackend`` (deepspeed/comm/torch.py:96), the
    collectives themselves are not methods here: inside ``jit``/``shard_map``
    they are ``jax.lax`` primitives over named mesh axes (see
    ``deepspeed_tpu.comm.comm``).  This class owns only process-level state.
    """

    def __init__(self):
        super().__init__("xla")

    def init_process_group(
        self,
        coordinator_address: str | None = None,
        num_processes: int | None = None,
        process_id: int | None = None,
        **kwargs,
    ) -> None:
        import jax

        if num_processes is not None and num_processes > 1:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
            except Exception:
                # jax sets its global client/service state BEFORE connecting;
                # without this reset a retry would die on jax's "initialize
                # should only be called once" guard instead of reconnecting
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
                raise
        self.initialized = True

    def get_rank(self) -> int:
        import jax

        return jax.process_index()

    def get_world_size(self) -> int:
        import jax

        return jax.process_count()

    def destroy_process_group(self) -> None:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        self.initialized = False
