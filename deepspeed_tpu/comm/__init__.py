from .backend import Backend, XlaBackend
from .comm import (
    ReduceOp,
    all_gather,
    all_gather_into_tensor,
    all_reduce,
    all_to_all,
    all_to_all_single,
    barrier,
    broadcast,
    configure,
    destroy_process_group,
    get_axis_index,
    get_local_rank,
    get_rank,
    get_world_size,
    host_broadcast,
    inference_all_reduce,
    init_distributed,
    is_initialized,
    log_summary,
    monitored_barrier,
    ppermute,
    reduce_scatter,
    reduce_scatter_tensor,
    send_recv_shift,
)

__all__ = [n for n in dir() if not n.startswith("_")]
