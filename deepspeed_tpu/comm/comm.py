"""``deepspeed_tpu.comm`` — uniform collectives facade.

Reference analogue: ``deepspeed/comm/comm.py:222-527`` (module-level
broadcast/all_gather/reduce_scatter/all_to_all/send/recv/barrier) and
``init_distributed`` (:625).

TPU-native semantics: collectives are ``jax.lax`` primitives over **named mesh
axes** and must run inside a ``jit``/``shard_map`` region whose mesh binds those
axes.  ``group`` arguments accept either a DeepSpeed group name (resolved via
:mod:`deepspeed_tpu.runtime.topology`, e.g. ``"data_parallel"``) or raw axis
name(s) (``"data"``, ``("data", "expert")``).  Host-level operations (barrier,
process bootstrap, cross-process value sync) go through ``jax.distributed`` /
``multihost_utils``.

Every facade op is wrapped with comms logging: in-jit ops record message
size/axes at trace time (once per compiled program — per-step device latency is
not host-observable under XLA), host-blocking ops record wall-clock latency.
"""
from __future__ import annotations

import functools
import os
import time
from enum import Enum
from typing import Any, Optional, Sequence, Tuple, Union

from ..runtime.fault import injection as _fault_injection
from ..runtime.fault.retry import RetryPolicy as _RetryPolicy
from ..runtime.fault.retry import retryable
from ..utils.comms_logging import CommsLogger, get_caller_func
from ..utils.logging import logger
from .backend import XlaBackend

GroupLike = Union[None, str, Sequence[str]]


class ReduceOp(Enum):
    SUM = 0
    AVG = 1
    PRODUCT = 2
    MIN = 3
    MAX = 4


cdb: Optional[XlaBackend] = None  # "communication data backend", reference naming
comms_logger = CommsLogger()
_MESH_AXIS_FALLBACK: Tuple[str, ...] = ()


# --------------------------------------------------------------------- #
# Initialization / process-level API
# --------------------------------------------------------------------- #
def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,
    coordinator_address: Optional[str] = None,
    world_size: Optional[int] = None,
    rank: Optional[int] = None,
    config: Optional[dict] = None,
    **kwargs,
) -> None:
    """Bootstrap multi-process JAX (reference: comm/comm.py:625).

    Single-process (the common TPU-pod-slice-per-host case before
    ``jax.distributed``) is a no-op besides flagging initialization.  Env
    discovery order: explicit args → ``COORDINATOR_ADDRESS``/``WORLD_SIZE``/
    ``RANK`` → OMPI env vars (mirrors mpi_discovery, comm/comm.py:694).
    """
    global cdb
    if cdb is not None and cdb.is_initialized():
        return
    if dist_backend != "xla":
        logger.warning(f"dist_backend={dist_backend!r} requested; TPU build always uses 'xla'")

    distributed_port = kwargs.pop("distributed_port", None)
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator_address is None and os.environ.get("MASTER_ADDR"):
        port = distributed_port or os.environ.get("MASTER_PORT", "29500")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if world_size is None:
        for var in ("DSTPU_WORLD_SIZE", "WORLD_SIZE", "OMPI_COMM_WORLD_SIZE",
                    "PMI_SIZE", "SLURM_NTASKS"):
            if os.environ.get(var):
                world_size = int(os.environ[var])
                break
    if rank is None:
        # launcher env → MPI (openmpi/mpich) → slurm → pdsh hostname lookup
        for var in ("DSTPU_RANK", "RANK", "OMPI_COMM_WORLD_RANK", "PMI_RANK",
                    "SLURM_PROCID"):
            if os.environ.get(var):
                rank = int(os.environ[var])
                break
    if rank is None and os.environ.get("DSTPU_NODE_LIST"):
        import socket

        hosts = os.environ["DSTPU_NODE_LIST"].split(",")
        # exact matches only: hostname, FQDN, short name, or a local IP —
        # fuzzy first-label matching would collide across clusters
        names = {socket.gethostname(), socket.getfqdn(),
                 socket.gethostname().split(".")[0]}
        try:
            names.update(i[4][0] for i in socket.getaddrinfo(
                socket.gethostname(), None))
        except socket.gaierror:
            pass
        matches = [i for i, h in enumerate(hosts) if h in names]
        if len(matches) == 1:
            rank = matches[0]
        else:
            raise RuntimeError(
                f"cannot derive rank from DSTPU_NODE_LIST={hosts}: host "
                f"identities {sorted(names)} matched {matches} — set RANK "
                f"explicitly or use a hostname-based hostfile")

    cdb = XlaBackend()
    retryable("comm_init", policy=_comm_init_policy())(_init_process_group)(
        cdb,
        coordinator_address=coordinator_address,
        num_processes=world_size,
        process_id=rank,
    )
    if config:
        configure(config)


def _comm_init_policy():
    """Backoff policy for the bootstrap (DSTPU_RETRY_* env — this runs before
    any config exists), extended to retry jax's coordinator errors:
    ``jax.distributed.initialize`` surfaces a refused/timed-out coordinator
    connection as ``JaxRuntimeError``, not ``OSError``."""
    import dataclasses

    base = _RetryPolicy.from_env()
    retry_on = base.retry_on
    try:
        from jax.errors import JaxRuntimeError

        retry_on = retry_on + (JaxRuntimeError,)
    except ImportError:
        pass
    return dataclasses.replace(base, retry_on=retry_on)


def _init_process_group(backend: XlaBackend, **kwargs) -> None:
    """Bootstrap body, retried with backoff+jitter: under gang restarts the
    coordinator routinely comes up seconds after its workers, and one refused
    connection must not kill a fresh worker group."""
    _fault_injection.inject("comm_init")
    backend.init_process_group(**kwargs)


def is_initialized() -> bool:
    return cdb is not None and cdb.is_initialized()


def get_rank() -> int:
    """Process rank (host index), not per-device rank."""
    return cdb.get_rank() if is_initialized() else _proc_index()


def get_world_size(group: GroupLike = None) -> int:
    """Device count of ``group`` (or process count when group is None)."""
    if group is not None:
        return _axis_size(_resolve_axes(group))
    return cdb.get_world_size() if is_initialized() else _proc_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))

def get_device_rank() -> int:
    """Flat rank of this process's first addressable device in the global order."""
    import jax

    return jax.local_devices()[0].id


def destroy_process_group() -> None:
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None


def _proc_index() -> int:
    import jax

    return jax.process_index()


def _proc_count() -> int:
    import jax

    return jax.process_count()


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
    """Configure comms logging (reference: comm/comm.py:185)."""
    if config is not None:
        cl = config.get("comms_logger", {}) if isinstance(config, dict) else {}
        comms_logger.configure(
            enabled=cl.get("enabled"), verbose=cl.get("verbose"),
            prof_all=cl.get("prof_all"), prof_ops=cl.get("prof_ops"))
    comms_logger.configure(enabled=enabled, prof_all=prof_all,
                           prof_ops=prof_ops, verbose=verbose)


def log_summary(show_straggler: bool = False):
    return comms_logger.log_summary(show_straggler)


# --------------------------------------------------------------------- #
# Axis resolution
# --------------------------------------------------------------------- #
def _resolve_axes(group: GroupLike) -> Tuple[str, ...]:
    """Group name or axis name(s) → concrete mesh axis tuple."""
    from ..runtime.topology import AXIS_ORDER, GROUP_AXES, get_topology

    if group is None:
        topo = get_topology()
        return tuple(a for a in AXIS_ORDER if topo.dims.get(a, 1) > 1) or (AXIS_ORDER[1],)
    if isinstance(group, str):
        if group in GROUP_AXES:
            return GROUP_AXES[group]
        if group in AXIS_ORDER:
            return (group,)
        raise KeyError(f"unknown group/axis {group!r}")
    return tuple(group)


def _axis_size(axes: Tuple[str, ...]) -> int:
    from ..runtime.topology import get_topology

    topo = get_topology()
    size = 1
    for a in axes:
        size *= topo.dims.get(a, 1)
    return size


def _active_axes(axes: Tuple[str, ...]) -> Tuple[str, ...]:
    """Drop size-1 mesh axes: collectives over them are no-ops, and JAX's
    varying-state checks reject reductions over axes a value doesn't vary on."""
    from ..runtime.topology import get_topology

    topo = get_topology()
    return tuple(a for a in axes if topo.dims.get(a, 1) > 1)


def _nbytes(x: Any) -> int:
    import numpy as np

    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def timed_op(fn):
    """Log facade collectives (reference decorator: comm/comm.py:101).

    For in-jit collectives, invocation here is a *trace*; we log the message
    size and a zero latency marker.  Host-blocking ops measure real wall time.
    Records flow to the comms logger (when enabled) and are aggregated into
    the telemetry metrics registry (when a telemetry hub is installed) —
    either can be on without the other.
    """
    from ..telemetry import get_telemetry
    from ..utils.comms_logging import record_comm_telemetry

    @functools.wraps(fn)
    def wrapper(*args, log_name: Optional[str] = None, **kwargs):
        name = log_name or fn.__name__
        log_comms = comms_logger.should_log(name)
        if not log_comms and get_telemetry() is None:
            return fn(*args, **kwargs)
        size = _nbytes(args[0]) if args else 0
        t0 = time.time()
        out = fn(*args, **kwargs)
        group = kwargs.get("group")
        n = _axis_size(_resolve_axes(group))
        # An abstract-tracer result means this invocation was a jit TRACE:
        # the measured wall time is compile bookkeeping, not a transfer, and
        # must not pollute the latency/bandwidth aggregates.
        trace_time = _is_tracer(out)
        if log_comms:
            # append() aggregates into the telemetry registry too
            comms_logger.append(fn.__name__, name, size, time.time() - t0, n,
                                trace_time=trace_time)
        else:
            record_comm_telemetry(fn.__name__, size, time.time() - t0, n,
                                  trace_time=trace_time)
        return out

    return wrapper


_TRACER_TYPES: Optional[tuple] = None


def _is_tracer(x: Any) -> bool:
    global _TRACER_TYPES
    if _TRACER_TYPES is None:
        types = []
        for locate in ("jax.core", "jax._src.core"):
            try:
                import importlib

                types.append(importlib.import_module(locate).Tracer)
                break
            except (ImportError, AttributeError):
                continue
        _TRACER_TYPES = tuple(types)
    if _TRACER_TYPES:
        return isinstance(x, _TRACER_TYPES)
    # Tracer class relocated again: duck-type rather than silently treating
    # trace-time invocations as real transfers
    return type(x).__name__.endswith("Tracer")


# --------------------------------------------------------------------- #
# In-jit collectives (use inside jit / shard_map with bound mesh axes)
# --------------------------------------------------------------------- #
@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None):
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(tensor, axes)
        if op == ReduceOp.AVG:
            out = out / _axis_size(axes)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(tensor, axes)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(tensor, axes)
    if op == ReduceOp.PRODUCT:
        import jax.numpy as jnp

        return jnp.exp(jax.lax.psum(jnp.log(tensor), axes))
    raise ValueError(f"unsupported reduce op {op}")


# DeepSpeed exposes ``inference_all_reduce`` as a separate low-latency op
# (comm/comm.py:506); on TPU it is the same XLA psum.
inference_all_reduce = all_reduce


@timed_op
def all_gather(tensor, group: GroupLike = None, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` (reference all_gather_into_tensor, comm/torch.py:259)."""
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    return jax.lax.all_gather(tensor, axes, axis=axis, tiled=tiled)


# reference naming compatibility
all_gather_into_tensor = all_gather


@timed_op
def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None,
                   scatter_dim: int = 0, tiled: bool = True):
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    out = jax.lax.psum_scatter(tensor, axes, scatter_dimension=scatter_dim, tiled=tiled)
    if op == ReduceOp.AVG:
        out = out / _axis_size(axes)
    return out


reduce_scatter_tensor = reduce_scatter


@timed_op
def all_to_all_single(tensor, group: GroupLike = None, split_axis: int = 0,
                      concat_axis: int = 0, tiled: bool = True):
    """All-to-all over the group axis (reference: comm/torch.py:297).

    Splits ``tensor`` along ``split_axis`` into group_size pieces, exchanges
    piece *i* with rank *i*, concatenates received pieces along ``concat_axis``.
    This is the Ulysses / MoE dispatch primitive.
    """
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    axis_name = axes if len(axes) > 1 else axes[0]
    return jax.lax.all_to_all(tensor, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


all_to_all = all_to_all_single


@timed_op
def broadcast(tensor, src: int = 0, group: GroupLike = None):
    """Broadcast rank-``src``'s value over the group axis.

    In-SPMD implementation: select src's slice via masked psum — every rank
    contributes its value iff its index along the axis equals ``src``.
    """
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    idx = _flat_axis_index(axes)
    mask = (idx == src).astype(tensor.dtype)
    return jax.lax.psum(tensor * mask, axes)


def _flat_axis_index(axes: Tuple[str, ...]):
    """Flattened index of this shard along the (possibly multi-)axis group."""
    import jax

    from ..runtime.topology import get_topology

    topo = get_topology()
    idx = 0
    for a in axes:
        if topo.dims.get(a, 1) > 1:
            idx = idx * topo.dims[a] + jax.lax.axis_index(a)
    return idx


def get_axis_index(group: GroupLike = None):
    """This shard's rank within the group (in-jit)."""
    import jax.numpy as jnp

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return jnp.zeros((), jnp.int32)
    return _flat_axis_index(axes)


@timed_op
def ppermute(tensor, perm, group: GroupLike = None):
    """Point-to-point permutation over the group axis (ring/p2p primitive)."""
    import jax

    axes = _active_axes(_resolve_axes(group))
    if not axes:
        return tensor
    axis_name = axes if len(axes) > 1 else axes[0]
    return jax.lax.ppermute(tensor, axis_name, perm)


def send_recv_shift(tensor, shift: int = 1, group: GroupLike = None):
    """Ring shift: every rank sends to (rank+shift) % n — pipeline/ring building block."""
    n = _axis_size(_resolve_axes(group))
    perm = [(i, (i + shift) % n) for i in range(n)]
    return ppermute(tensor, perm, group=group)


# --------------------------------------------------------------------- #
# Host-level (outside-jit) operations
# --------------------------------------------------------------------- #
@timed_op
def barrier(group: GroupLike = None):
    """Cross-process barrier (host-level)."""
    import jax

    if _proc_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")
    else:
        jax.effects_barrier()


def host_broadcast(value, src: int = 0):
    """Broadcast a host value from process ``src`` to all processes."""
    if _proc_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value, is_source=_proc_index() == src)


def monitored_barrier(group: GroupLike = None, timeout=None):
    return barrier(group)
