"""Top-level kernel layer: Pallas kernels whose EDGES are collectives.

``inference/v2/kernels`` holds the serving attention kernels;
``ops/quantizer`` the wire quantizers; this package holds the T3-style
compute+collective fusions (arXiv:2401.16677) where a matmul's epilogue or
prologue IS a collective exchange — see ``fused_collective_matmul``.
"""
from .fused_collective_matmul import (  # noqa: F401
    all_gather_matmul,
    matmul_reduce_scatter,
    matmul_reference,
    rmsnorm_matmul,
    rmsnorm_matmul_reference,
    supports_fused_rmsnorm,
)
