"""T3-style fused compute+collective matmul kernels (arXiv:2401.16677).

Scheduler-level overlap (PR 4 deferral/bucketing, PR 9 wire/algorithm
selection) can only hide a collective behind OTHER work; T3's observation
is that the producing kernel itself is the best hiding place — walk the
output tiles in shard-major order and exchange each shard's block as it
completes, so the MXU keeps streaming while earlier shards are already on
the wire.  EQuARX (arXiv:2506.17615) shows the same tile-granular schedule
composes with quantized wires, which is why the int8 edges below ride the
PR-9 fused-wire kernels (``ops/quantizer/quantizer.py quant_pack_wire`` /
``unpack_dequant_mean``) unchanged.

Three kernels, each with the collective fused onto an edge:

  * :func:`matmul_reduce_scatter` — reduce-scatter EPILOGUE.  The Pallas
    grid walks output tiles shard-major (grid dim 0 = destination shard),
    so on TPU each completed shard block can enter the exchange while the
    MXU continues on the next shard.  Replaces the trailing
    ``psum_scatter`` on ZeRO grad buckets and TP row-parallel projections.
  * :func:`all_gather_matmul` — all-gather PROLOGUE for ZeRO-3 / TP
    column-parallel weight shards: tile k-loops begin on the
    locally-resident shard while remote shards stream in (the int8 edge
    dequantizes each arriving shard inside the consuming kernel).
  * :func:`rmsnorm_matmul` — RMSNorm folded into the consuming
    projection's kernel (the norm is memory-bound; recomputing it per
    output tile is free and saves the normalized activations' HBM
    round-trip).

Seams (the same discipline as the PR-9 wire kernels): ``impl="pallas"``
runs the Pallas kernels — interpreter mode off-TPU — and ``impl="dense"``
is the XLA lowering built from the *identical* composition, so the CPU sim
can assert the contracts the silicon relies on:

  * fp edge: BITWISE equality with the unfused matmul→collective
    composition (:func:`matmul_reference` followed by the plain
    collective) under both seams;
  * int8 edge: bitwise equality with unfused-matmul→PR-9-fused-wire, and
    the PR-9 half-step error bound vs the fp oracle (|err| ≤ 0.5 · group
    scale per exchanged element).

What the CPU sim canNOT measure — the tile-granular exchange actually
overlapping MXU time — is the on-silicon item the ROADMAP carries as
STILL OWED; here the fused property is asserted structurally (the
collective's operand chases through layout-only ops to the producing
``pallas_call`` — the ``fused-wire-layout`` dstpu-check pass, extended for
gemm edges).
"""
from __future__ import annotations

from functools import partial as _partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..ops.quantizer.quantizer import (
    quant_pack_wire,
    unpack_dequant_mean,
    unpack_dequant_wire,
)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def resolve_impl(impl: str = "auto") -> str:
    """"pallas" on TPU, "dense" elsewhere (``"auto"``); explicit values
    pass through — tests pin ``"pallas"`` to exercise interpreter mode."""
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "dense"
    if impl not in ("pallas", "dense"):
        raise ValueError(f"impl must be auto|pallas|dense, got {impl!r}")
    return impl


def _largest_divisor(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= cap (tile sizes must divide the
    array — Pallas partial blocks would pad the shard-major walk)."""
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


def matmul_reference(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """THE unfused matmul every parity contract in this module is defined
    against: f32 accumulation, output in the promoted input dtype.  The
    kernels' per-tile dots use the same primitive over the same contraction
    ordering, which is what makes the fp edges bitwise-comparable."""
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    return jnp.dot(x, w, preferred_element_type=jnp.float32).astype(out_dtype)


# --------------------------------------------------------------------- #
# Shard-major tiled matmul (the epilogue's producing kernel)
# --------------------------------------------------------------------- #
def _matmul_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = jnp.dot(x_ref[:], w_ref[:],
                       preferred_element_type=jnp.float32
                       ).astype(o_ref.dtype)


def shard_major_matmul(x: jnp.ndarray, w: jnp.ndarray, n_shards: int,
                       block_m: int = 256, block_n: int = 512
                       ) -> jnp.ndarray:
    """``x @ w`` as a Pallas kernel whose grid walks output tiles in
    SHARD-MAJOR order: grid dim 0 is the destination shard of the trailing
    reduce-scatter, so shard ``s``'s rows ``[s·M/n, (s+1)·M/n)`` complete
    before any tile of shard ``s+1`` starts — on TPU the epilogue exchange
    of shard ``s`` overlaps the MXU's work on shard ``s+1``.

    Full-K tiles (no k-loop): each output element is ONE dot over the same
    contraction ordering as :func:`matmul_reference`, keeping the fp edge
    bitwise.  ``M`` must divide by ``n_shards``.
    """
    M, K = x.shape
    K2, N = w.shape
    assert K == K2, (x.shape, w.shape)
    if M % n_shards:
        raise ValueError(f"rows {M} not divisible by {n_shards} shards")
    rows = M // n_shards
    bm = _largest_divisor(rows, block_m)
    bn = _largest_divisor(N, block_n)
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    return pl.pallas_call(
        _matmul_kernel,
        grid=(n_shards, rows // bm, N // bn),
        in_specs=[pl.BlockSpec((bm, K), lambda s, i, j:
                               (s * (rows // bm) + i, 0)),
                  pl.BlockSpec((K, bn), lambda s, i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda s, i, j:
                               (s * (rows // bm) + i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=_interpret(),
    )(x, w)


# --------------------------------------------------------------------- #
# (a) reduce-scatter epilogue
# --------------------------------------------------------------------- #
def matmul_reduce_scatter(x: jnp.ndarray, w: jnp.ndarray, axes,
                          wire_bits: int = 0, group_size: int = 256,
                          impl: str = "auto",
                          n: Optional[int] = None) -> jnp.ndarray:
    """``mean-reduce-scatter(x @ w)`` over ``axes`` along rows, with the
    matmul walked shard-major so the exchange is an epilogue of the kernel
    (must run inside shard_map with ``axes`` manual).

    Returns each rank's ``[M/n, N]`` mean partition.  ``wire_bits`` 8/4
    exchanges the epilogue on the PR-9 fused quantized wire (one
    quant+pack kernel per rank's output, ``all_to_all`` of wire bytes,
    fused ``unpack_dequant_mean`` on the receive side); 0 is the
    full-precision ``psum_scatter`` edge — bitwise vs
    ``psum_scatter(matmul_reference(x, w))/n``.
    """
    impl = resolve_impl(impl)
    if n is None:
        n = jax.lax.psum(1, axes)
    M, N = x.shape[0], w.shape[1]
    if M % max(n, 1):
        raise ValueError(f"rows {M} not divisible by group size {n}")
    y = shard_major_matmul(x, w, max(n, 1)) if impl == "pallas" \
        else matmul_reference(x, w)
    if n <= 1:
        return y
    if wire_bits:
        flat = y.reshape(-1).astype(jnp.float32)       # layout-only hop
        chunk = flat.shape[0] // n                     # one shard's block
        if chunk % group_size:
            raise ValueError(
                f"per-shard block of {chunk} elements not divisible by "
                f"quantization group_size={group_size}; pick N so that "
                f"(M/n)·N aligns (production shapes are 128-multiples)")
        wv, s = quant_pack_wire(flat, wire_bits, group_size)
        gpc = wv.shape[0] // n
        w_x = jax.lax.all_to_all(wv.reshape(n, gpc, wv.shape[1]), axes,
                                 split_axis=0, concat_axis=0, tiled=True)
        s_x = jax.lax.all_to_all(s.reshape(n, gpc, 1), axes,
                                 split_axis=0, concat_axis=0, tiled=True)
        mine = unpack_dequant_mean(w_x, s_x, wire_bits, n)
        return mine.reshape(M // n, N).astype(y.dtype)
    part = jax.lax.psum_scatter(y, axes, scatter_dimension=0, tiled=True)
    return part / n


# --------------------------------------------------------------------- #
# (b) all-gather prologue
# --------------------------------------------------------------------- #
def _gathered_dequant_matmul(x, w_wire, s_wire, wire_bits, k_shard, N,
                             out_dtype):
    """One kernel: per arriving shard, unpack+dequantize its weight block
    and accumulate its k-slice dot — the int8 prologue's consuming kernel.
    The shard loop is static (``n`` known at trace time); on TPU each
    iteration's wire block is what just streamed in, so the local shard's
    k-block starts with zero wait.  Accumulation is per-shard partial sums
    (the int8 edge is bound-checked, not bitwise — only the fp edge must
    match the single-dot ordering).  ``out_dtype`` is the caller's
    promote(x, w_shard) so the pallas and dense seams agree for bf16
    weights."""
    n = w_wire.shape[0]
    M = x.shape[0]

    def kernel(x_ref, w_ref, s_ref, o_ref):
        acc = jnp.zeros((M, N), jnp.float32)
        for r in range(n):
            wr = w_ref[r]                                    # [g, W]
            vals = unpack_dequant_wire_values(wr, s_ref[r], wire_bits)
            w_r = vals.reshape(-1)[:k_shard * N].reshape(k_shard, N)
            xk = x_ref[:, r * k_shard:(r + 1) * k_shard]
            acc = acc + jnp.dot(xk.astype(jnp.float32), w_r,
                                preferred_element_type=jnp.float32)
        o_ref[:] = acc.astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=_interpret(),
    )(x, w_wire, s_wire)


def unpack_dequant_wire_values(w: jnp.ndarray, scales: jnp.ndarray,
                               bits: int) -> jnp.ndarray:
    """In-kernel unpack+dequant: the quantizer's ``_unpack_wire`` (plain
    jnp ops — usable inside another Pallas kernel body, unlike its
    ``pallas_call`` wrappers) plus the scale multiply, so the wire's
    half-split nibble layout stays single-sourced."""
    from ..ops.quantizer.quantizer import _unpack_wire

    return _unpack_wire(w, bits).astype(jnp.float32) * scales


def all_gather_matmul(x: jnp.ndarray, w_shard: jnp.ndarray, axes,
                      wire_bits: int = 0, group_size: int = 256,
                      impl: str = "auto",
                      n: Optional[int] = None) -> jnp.ndarray:
    """``x @ all_gather(w_shard)`` with the gather fused as the matmul's
    PROLOGUE (must run inside shard_map with ``axes`` manual).

    ``w_shard`` is this rank's ``[K/n, N]`` row block of the weight (the
    ZeRO-3 param shard / TP column-parallel k-slice).  fp edge: the
    gathered full weight feeds the shard-major Pallas matmul — bitwise vs
    ``matmul_reference(x, all_gather(w_shard))``.  int8 edge: the wire on
    the gather is the PR-9 quant+pack kernel's output and the consuming
    kernel dequantizes each shard block as it arrives, k-looping
    shard-by-shard (locally-resident shard first on TPU).
    """
    impl = resolve_impl(impl)
    if n is None:
        n = jax.lax.psum(1, axes)
    k_shard, N = w_shard.shape
    if n <= 1:
        return matmul_reference(x, w_shard) if impl == "dense" \
            else shard_major_matmul(x, w_shard, 1)
    if wire_bits:
        flat = w_shard.reshape(-1)
        wv, s = quant_pack_wire(flat, wire_bits, group_size)
        w_all = jax.lax.all_gather(wv, axes, axis=0, tiled=False)
        s_all = jax.lax.all_gather(s, axes, axis=0, tiled=False)
        if impl == "pallas":
            return _gathered_dequant_matmul(
                x, w_all, s_all, wire_bits, k_shard, N,
                jnp.promote_types(x.dtype, w_shard.dtype))
        padded = wv.shape[0] * group_size
        vals = unpack_dequant_wire(w_all.reshape(-1, wv.shape[1]),
                                   s_all.reshape(-1, 1), wire_bits)
        w_full = vals.reshape(n, padded)[:, :k_shard * N].reshape(-1, N)
        return matmul_reference(x, w_full.astype(w_shard.dtype))
    w_full = jax.lax.all_gather(w_shard, axes, axis=0, tiled=True)
    if impl == "pallas":
        return shard_major_matmul(x, w_full, 1)
    return matmul_reference(x, w_full)


# --------------------------------------------------------------------- #
# (c) fused RMSNorm + matmul epilogue
# --------------------------------------------------------------------- #
def rmsnorm_matmul_reference(x: jnp.ndarray, scale: jnp.ndarray,
                             w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """The unfused composition (``models/transformer.py rms_norm`` followed
    by the projection matmul) the fused kernel is parity-checked against."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    h = (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale
    return matmul_reference(h, w)


def _rmsnorm_matmul_kernel(eps, x_ref, s_ref, w_ref, o_ref):
    x = x_ref[:]
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    h = (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * s_ref[:]
    o_ref[:] = jnp.dot(h, w_ref[:],
                       preferred_element_type=jnp.float32
                       ).astype(o_ref.dtype)


@_partial(jax.custom_vjp, nondiff_argnums=(0, 4, 5))
def _rmsnorm_matmul_pallas(eps, x2, scale, w, block_m, block_n):
    """Fused kernel over ``x2 [M, D] @ w [D, F]`` with a custom VJP: the
    forward is the Pallas kernel, the backward differentiates the
    reference composition (same math — the forward is bitwise against it,
    test-asserted — so the cotangents are the unfused path's).  Without
    this, ``jax.grad`` through the ``pallas_call`` raises and the
    ``fused_rmsnorm="auto"`` default would break TPU *training* (the same
    reason ``flash_attention`` carries a custom VJP)."""
    M, D = x2.shape
    F = w.shape[1]
    bm = _largest_divisor(M, block_m)
    bn = _largest_divisor(F, block_n)
    out_dtype = jnp.promote_types(x2.dtype, w.dtype)
    return pl.pallas_call(
        _partial(_rmsnorm_matmul_kernel, eps),
        grid=(M // bm, F // bn),
        in_specs=[pl.BlockSpec((bm, D), lambda i, j: (i, 0)),
                  pl.BlockSpec((1, D), lambda i, j: (0, 0)),
                  pl.BlockSpec((D, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, F), out_dtype),
        interpret=_interpret(),
    )(x2, scale, w)


def _rmsnorm_matmul_fwd(eps, x2, scale, w, block_m, block_n):
    return _rmsnorm_matmul_pallas(eps, x2, scale, w, block_m, block_n), \
        (x2, scale, w)


def _rmsnorm_matmul_bwd(eps, _block_m, _block_n, res, g):
    x2, scale, w = res
    _, vjp = jax.vjp(
        lambda x, s, ww: rmsnorm_matmul_reference(x, s.reshape(-1), ww,
                                                  eps), x2, scale, w)
    dx, ds, dw = vjp(g)
    return dx, ds.reshape(scale.shape), dw


_rmsnorm_matmul_pallas.defvjp(_rmsnorm_matmul_fwd, _rmsnorm_matmul_bwd)


def rmsnorm_matmul(x: jnp.ndarray, scale: jnp.ndarray, w: jnp.ndarray,
                   eps: float, impl: str = "auto",
                   block_m: int = 256, block_n: int = 512) -> jnp.ndarray:
    """``rms_norm(x, scale, eps) @ w`` in one kernel: the norm's variance/
    rsqrt is recomputed per output row tile (VPU work over rows already in
    VMEM for the dot), so the normalized activations never round-trip HBM.

    ``x`` may carry leading batch dims; the last dim contracts with ``w``
    ``[D, F]``.  Per-tile math is the exact ``rms_norm`` composition, so
    the fused kernel is bitwise against
    :func:`rmsnorm_matmul_reference` — test-asserted.  Differentiable:
    the Pallas path carries a custom VJP whose backward is the reference
    composition's (training through the fused model works).
    """
    impl = resolve_impl(impl)
    if impl == "dense":
        return rmsnorm_matmul_reference(x, scale, w, eps)
    lead = x.shape[:-1]
    D = x.shape[-1]
    x2 = x.reshape(-1, D)
    out = _rmsnorm_matmul_pallas(float(eps), x2, scale.reshape(1, D), w,
                                 block_m, block_n)
    return out.reshape(lead + (w.shape[1],))


def supports_fused_rmsnorm() -> bool:
    """Whether the fused RMSNorm+matmul path should be used by default —
    TPU only (the CPU sim keeps the unfused jaxpr so tier-1 numerics and
    compile behavior are unchanged; parity is asserted through the
    interpreter seam in the kernel tests)."""
    try:
        from ..accelerator import get_accelerator

        return bool(get_accelerator().supports_pallas())
    except Exception:  # noqa: BLE001 — conservative off
        return False


# --------------------------------------------------------------------- #
# Analytic cost (the kernel_sweep roofline + selector inputs)
# --------------------------------------------------------------------- #
def matmul_costs(M: int, K: int, N: int,
                 dtype_bytes: int = 4) -> Tuple[float, float]:
    """(flops, hbm bytes) of one ``[M,K]@[K,N]`` — the kernel_sweep's
    %-of-peak numerator for the fused-gemm family."""
    flops = 2.0 * M * K * N
    bytes_ = float(dtype_bytes) * (M * K + K * N + M * N)
    return flops, bytes_
