"""Reconstruct a full fp32 state dict from a (sharded) checkpoint.

Reference: ``deepspeed/utils/zero_to_fp32.py:40,391`` — the offline script the
engine copies into every checkpoint dir so users can export ZeRO shards to a
single consolidated file.

On TPU the checkpoint is orbax/tensorstore: arrays are stored with global
shape + per-shard metadata, so "consolidation" is simply a host-side restore —
no shard-merging math like the reference needs for its flat-buffer ZeRO
partitions.  Provided as both an API and a CLI.
"""
from __future__ import annotations

import argparse
import os
import pickle
from typing import Any, Dict, Optional

import numpy as np


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None) -> Dict[str, Any]:
    """Load params from a checkpoint as host fp32 numpy arrays, flattened to
    '/'.joined names (reference fn name kept)."""
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            raise ValueError(f"no 'latest' file in {checkpoint_dir}; pass tag")
    path = os.path.join(checkpoint_dir, str(tag), "state")
    with ocp.PyTreeCheckpointer() as ckptr:
        state = ckptr.restore(path)
    params = state["params"] if isinstance(state, dict) and "params" in state else state

    flat: Dict[str, Any] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node, dtype=np.float32)

    walk("", params)
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: Optional[str] = None) -> None:
    """Write the consolidated dict to ``output_file`` (pickle of name→ndarray;
    loadable without jax)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    with open(output_file, "wb") as f:
        pickle.dump(sd, f)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)
    print(f"saved fp32 state dict to {args.output_file}")


if __name__ == "__main__":
    main()
