"""Universal checkpoint conversion CLI (reference: deepspeed/checkpoint/
ds_to_universal.py:469 main; extract :112/:152, TP-slice merge :232).

The reference needs a multi-stage offline pipeline because its ZeRO shards
are rank-local flat-buffer slices entangled with TP/PP layout.  Here the
engine's checkpoints already carry a logical layout manifest
(``checkpoint/universal/layout.py``) and reshard on load — so ``convert``
is an *exporter*: it validates the source tag against the PR-1 integrity
manifest, then materializes the engine checkpoint into the reference's
offline universal layout (one directory per parameter holding ``fp32.npy``
plus adam moments named ``exp_avg``/``exp_avg_sq``), each array saved with
an **explicit dtype contract**: the stored dtype is recorded in
``index.json`` and re-applied on load, so bf16 leaves survive the numpy
round trip (a raw ``np.save``/``np.load`` of an ml_dtypes array comes back
as opaque ``|V2`` bytes).

  * :func:`convert` — engine checkpoint → universal dir (``--tag``
    verified against ``fault/manifest.py`` before any byte is read);
  * :func:`load_universal` — universal dir → flat ``{name: ndarray}``
    with faithful dtypes (``load_universal_checkpoint`` path,
    universal_checkpoint.py:22);
  * the same CLI surface as the reference script.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, Optional

import numpy as np

UNIVERSAL_SUBDIR = "zero"  # reference layout: <dir>/zero/<param>/fp32.pt etc.
INDEX_FILE = "index.json"

# one tree-flattening convention for the whole universal-checkpoint stack
from .universal.layout import flat_values as _flatten  # noqa: E402


def _np_with_dtype(arr: Any) -> np.ndarray:
    """Host ndarray preserving the logical dtype (bf16 via ml_dtypes)."""
    import ml_dtypes  # ships with jax

    a = np.asarray(arr)
    if a.dtype == np.dtype("V2"):  # raw bf16 bytes from a typeless source
        a = a.view(ml_dtypes.bfloat16)
    return a


def _save_leaf(pdir: str, fname: str, arr: np.ndarray) -> Dict[str, Any]:
    """Write one array; bf16/fp8 save as their raw bytes, the dtype
    contract lives in index.json."""
    np.save(os.path.join(pdir, fname), arr)
    return {"file": fname + ".npy", "dtype": arr.dtype.name,
            "shape": list(arr.shape)}


def _load_leaf(pdir: str, rec: Dict[str, Any]) -> np.ndarray:
    import ml_dtypes

    raw = np.load(os.path.join(pdir, rec["file"]))
    want = rec.get("dtype")
    if want and raw.dtype.name != want:
        try:
            dt = np.dtype(want)
        except TypeError:
            dt = np.dtype(getattr(ml_dtypes, want))
        # numpy reloads exotic dtypes as void bytes of equal width — a
        # view restores the logical type losslessly; a genuine dtype
        # change (legacy fp32 export) casts
        raw = raw.view(dt) if raw.dtype.itemsize == dt.itemsize and \
            raw.dtype.kind == "V" else raw.astype(dt)
    return raw


def convert(checkpoint_dir: str, output_dir: str, tag: Optional[str] = None,
            strict: bool = True) -> str:
    """Engine checkpoint → universal dir.  Returns the tag converted.

    ``strict`` verifies the source tag against its integrity manifest
    (``fault/manifest.py``) before conversion — a torn checkpoint must
    fail here, not produce a silently-wrong universal export."""
    import orbax.checkpoint as ocp

    from ..runtime.fault.manifest import verify_checkpoint
    from .universal.layout import read_layout

    if tag is None:
        from ..runtime.checkpoint_engine.orbax_checkpoint_engine import \
            OrbaxCheckpointEngine

        tag = OrbaxCheckpointEngine(checkpoint_dir).latest_tag()
        if tag is None:
            raise FileNotFoundError(
                f"{checkpoint_dir}: no valid committed checkpoint tag")
    src = os.path.join(checkpoint_dir, str(tag))
    if strict:
        verify_checkpoint(src)  # raises CheckpointCorruptError naming the damage
    layout = read_layout(src)

    with ocp.PyTreeCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(src, "state"))

    os.makedirs(os.path.join(output_dir, UNIVERSAL_SUBDIR), exist_ok=True)
    params = _flatten(state["params"] if isinstance(state, dict) else state)
    # optax adam-family states: mu/nu subtrees mirror the param tree; their
    # flattened suffixes match param names exactly
    opt_flat = _flatten(state.get("opt_state", {})
                        if isinstance(state, dict) else {})
    moments: Dict[str, Dict[str, Any]] = {}
    for name, arr in opt_flat.items():
        for marker, uname in (("mu/", "exp_avg"), ("nu/", "exp_avg_sq")):
            if f"/{marker}" in f"/{name}":
                moments.setdefault(name.split(marker, 1)[-1], {})[uname] = arr

    index: Dict[str, Any] = {"version": 2, "source_tag": str(tag),
                             "params": {}}
    for name, arr in params.items():
        pdir = os.path.join(output_dir, UNIVERSAL_SUBDIR, name.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        a = _np_with_dtype(arr)
        rec = {"leaves": {"param": _save_leaf(pdir, "fp32", a)}}
        for mname, marr in moments.get(name, {}).items():
            rec["leaves"][mname] = _save_leaf(pdir, mname,
                                              _np_with_dtype(marr))
        index["params"][name] = rec

    step = 0
    if isinstance(state, dict) and state.get("global_step") is not None:
        step = int(np.asarray(state["global_step"]))
    index["step"] = step
    if layout is not None:
        index["source_mesh"] = layout.get("mesh")
        index["zero_stage"] = layout.get("zero_stage")
    with open(os.path.join(output_dir, INDEX_FILE), "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)
    # kept for readers of the old stub format
    with open(os.path.join(output_dir, "universal_meta.json"), "w") as f:
        json.dump({"step": step, "source_tag": str(tag)}, f)
    return str(tag)


def load_universal(universal_dir: str,
                   include_moments: bool = False) -> Dict[str, Any]:
    """Universal dir → flat ``{param_name: ndarray}`` with faithful dtypes.

    ``include_moments=True`` returns
    ``{name: {"param": ..., "exp_avg": ..., "exp_avg_sq": ...}}`` instead.
    Pre-index (v1) exports load as before (fp32, dtype contract unknown).
    """
    zdir = os.path.join(universal_dir, UNIVERSAL_SUBDIR)
    index_path = os.path.join(universal_dir, INDEX_FILE)
    out: Dict[str, Any] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        for name, rec in index["params"].items():
            pdir = os.path.join(zdir, name.replace("/", "."))
            leaves = {ln: _load_leaf(pdir, lrec)
                      for ln, lrec in rec["leaves"].items()}
            out[name] = leaves if include_moments else leaves["param"]
        return out
    for pname in sorted(os.listdir(zdir)):               # legacy v1 layout
        pdir = os.path.join(zdir, pname)
        fp32 = os.path.join(pdir, "fp32.npy")
        if not os.path.exists(fp32):
            continue
        name = pname.replace(".", "/")
        if not include_moments:
            out[name] = np.load(fp32)
            continue
        leaves = {"param": np.load(fp32)}
        for mname in ("exp_avg", "exp_avg_sq"):          # v1 wrote these too
            mp = os.path.join(pdir, f"{mname}.npy")
            if os.path.exists(mp):
                leaves[mname] = np.load(mp)
        out[name] = leaves
    return out


def unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    tree: Dict[str, Any] = {}
    for name, arr in flat.items():
        node = tree
        parts = name.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Export an engine checkpoint to the offline universal "
                    "layout (per-param fp32 + adam moments, dtype-faithful)")
    parser.add_argument("--input_folder", required=True)
    parser.add_argument("--output_folder", required=True)
    parser.add_argument("--tag", default=None,
                        help="checkpoint tag (default: the committed "
                             "'latest', falling back to the newest valid "
                             "tag); verified against the integrity "
                             "manifest before conversion")
    parser.add_argument("--no_strict", action="store_true",
                        help="skip integrity verification of the source tag")
    parser.add_argument("--num_extract_workers", type=int, default=1)  # parity knob
    parser.add_argument("--num_merge_workers", type=int, default=1)
    args = parser.parse_args(argv)
    tag = convert(args.input_folder, args.output_folder, args.tag,
                  strict=not args.no_strict)
    print(f"universal checkpoint (tag {tag}) written to {args.output_folder}")


if __name__ == "__main__":
    main()
