"""Universal checkpoint conversion (reference: deepspeed/checkpoint/
ds_to_universal.py:469 main; extract :112/:152, TP-slice merge :232).

The reference needs a multi-stage offline pipeline because its ZeRO shards are
rank-local flat-buffer slices entangled with TP/PP layout.  Orbax checkpoints
are already layout-agnostic (global-shape arrays + shard metadata), so a
checkpoint saved on ANY mesh loads on any other — the "universal" property is
intrinsic.  This module therefore provides:

  * :func:`convert` — normalize any engine checkpoint into the explicit
    universal layout (one array per param, fp32, plus optimizer moments named
    ``exp_avg``/``exp_avg_sq`` like the reference's universal shards);
  * :func:`load_universal` — restore a universal dir into a live engine
    (the ``load_universal_checkpoint`` path, universal_checkpoint.py:22);
  * the same CLI surface as the reference script.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, Optional

import numpy as np

UNIVERSAL_SUBDIR = "zero"  # reference layout: <dir>/zero/<param>/fp32.pt etc.


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}/{k}" if prefix else str(k)))
    else:
        out[prefix] = tree
    return out


def convert(checkpoint_dir: str, output_dir: str, tag: Optional[str] = None) -> None:
    """Engine checkpoint → universal dir of per-param .npy files."""
    import orbax.checkpoint as ocp

    if tag is None:
        with open(os.path.join(checkpoint_dir, "latest")) as f:
            tag = f.read().strip()
    with ocp.PyTreeCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(checkpoint_dir, str(tag), "state"))

    os.makedirs(os.path.join(output_dir, UNIVERSAL_SUBDIR), exist_ok=True)
    params = _flatten(state["params"])
    # optax adam-family states: find mu/nu trees by shape-matched names
    opt_flat = _flatten(state.get("opt_state", {}))
    moments: Dict[str, Dict[str, Any]] = {}
    for name, arr in opt_flat.items():
        low = name.lower()
        if "/mu/" in low or low.startswith("mu/") or "/mu" == low[-3:]:
            moments.setdefault(name.split("mu/", 1)[-1], {})["exp_avg"] = arr
        elif "/nu/" in low or low.startswith("nu/"):
            moments.setdefault(name.split("nu/", 1)[-1], {})["exp_avg_sq"] = arr

    for name, arr in params.items():
        pdir = os.path.join(output_dir, UNIVERSAL_SUBDIR, name.replace("/", "."))
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"),
                np.asarray(arr, dtype=np.float32))
        for mname, marr in moments.get(name, {}).items():
            np.save(os.path.join(pdir, f"{mname}.npy"),
                    np.asarray(marr, dtype=np.float32))

    meta = {"step": int(np.asarray(state.get("global_step", 0))),
            "source_tag": str(tag)}
    with open(os.path.join(output_dir, "universal_meta.json"), "w") as f:
        json.dump(meta, f)


def load_universal(universal_dir: str) -> Dict[str, np.ndarray]:
    """Universal dir → flat {param_name: fp32 ndarray}."""
    zdir = os.path.join(universal_dir, UNIVERSAL_SUBDIR)
    out = {}
    for pname in sorted(os.listdir(zdir)):
        fp32 = os.path.join(zdir, pname, "fp32.npy")
        if os.path.exists(fp32):
            out[pname.replace(".", "/")] = np.load(fp32)
    return out


def unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    tree: Dict[str, Any] = {}
    for name, arr in flat.items():
        node = tree
        parts = name.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--input_folder", required=True)
    parser.add_argument("--output_folder", required=True)
    parser.add_argument("--tag", default=None)
    parser.add_argument("--num_extract_workers", type=int, default=1)  # parity knob
    parser.add_argument("--num_merge_workers", type=int, default=1)
    args = parser.parse_args()
    convert(args.input_folder, args.output_folder, args.tag)
    print(f"universal checkpoint written to {args.output_folder}")


if __name__ == "__main__":
    main()
