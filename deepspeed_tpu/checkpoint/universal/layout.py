"""Logical layout manifest: the save-side half of universal checkpoints.

A tensorstore checkpoint already stores arrays with their *global* shape, but
nothing in the directory says how the writing job was sharded, what the tree
structure was, or which leaves a resuming job may legitimately drop.  The
layout manifest (``layout.json``, written next to the PR-1 integrity
``manifest.json`` and covered by it) records exactly that:

  * a JSON **skeleton** of the saved tree in orbax's serialized form (dicts
    for mappings/named tuples/dataclasses, lists for tuples, ``null`` for
    empty nodes), with every array leaf replaced by a record of its global
    logical shape, dtype, and partition spec;
  * the writing mesh's axis dims + axis order, world size, and zero stage.

With that record a loader on ANY mesh can rebuild a restore template without
the writing job's python objects — the resharding planner
(:mod:`.planner`) maps source shards onto the target mesh and tensorstore
range-reads only the bytes each target shard needs.  This is the
layout-manifest idea cross-replica weight-update sharding (arXiv:2004.13336)
uses for sharded optimizer state, applied to the whole engine state.

Reference analogue: ``deepspeed/checkpoint/universal_checkpoint.py`` records
per-param ``PARAM_SHAPES``/patterns; here the layout *is* the tree.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...runtime.fault.atomic import atomic_write_text

LAYOUT_FILE = "layout.json"
LAYOUT_VERSION = 1
LEAF_KEY = "~leaf"
SEP = "/"


# --------------------------------------------------------------------- #
# serialization: live pytree -> orbax-form skeleton
# --------------------------------------------------------------------- #
def serialize_state(state: Any) -> Any:
    """``state`` in orbax's on-disk tree form: named tuples / flax struct
    dataclasses become dicts of field names, tuples become lists, empty
    nodes become None — the same normalization ``PyTreeCheckpointer``
    applies, so a template built from this skeleton matches the directory
    key-for-key."""
    from orbax.checkpoint import utils as _ou

    return _normalize(_ou.serialize_tree(state, keep_empty_nodes=True))


def _normalize(node: Any) -> Any:
    if isinstance(node, dict):
        return {str(k): _normalize(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_normalize(v) for v in node]
    # serialize_tree keeps zero-field NamedTuples (e.g. optax EmptyState) as
    # values; on disk they are empty nodes and restore as None
    if hasattr(node, "_fields") and not getattr(node, "_fields"):
        return None
    return node


def _spec_to_json(spec: Any) -> Optional[List[Any]]:
    """PartitionSpec -> JSON (tuple entries become lists)."""
    if spec is None:
        return None
    out: List[Any] = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def spec_from_json(entries: Optional[List[Any]]) -> Any:
    """JSON spec entries -> PartitionSpec (None -> replicated)."""
    from jax.sharding import PartitionSpec

    if entries is None:
        return PartitionSpec()
    return PartitionSpec(*[tuple(e) if isinstance(e, list) else e
                           for e in entries])


def _leaf_record(leaf: Any) -> Dict[str, Any]:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        # python scalar leaf (int step counters etc.)
        return {LEAF_KEY: 1, "shape": None,
                "dtype": type(leaf).__name__, "spec": None}
    rec: Dict[str, Any] = {LEAF_KEY: 1, "shape": [int(d) for d in shape],
                           "dtype": np.dtype(dtype).name, "spec": None}
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        rec["spec"] = _spec_to_json(spec)
    return rec


def _mesh_dims_of(state_serialized: Any) -> Optional[Dict[str, int]]:
    """Axis dims of the mesh the leaves live on (first NamedSharding wins —
    one training job has one global mesh)."""
    import jax

    for leaf in jax.tree.leaves(state_serialized):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape:
            return {str(k): int(v) for k, v in dict(shape).items()}
    return None


def build_layout(state: Any, extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The layout manifest for a live state pytree about to be saved."""
    serialized = serialize_state(state)

    def skel(node):
        if isinstance(node, dict):
            return {k: skel(v) for k, v in node.items()}
        if isinstance(node, list):
            return [skel(v) for v in node]
        if node is None:
            return None
        return _leaf_record(node)

    mesh_dims = _mesh_dims_of(serialized)
    layout: Dict[str, Any] = {
        "version": LAYOUT_VERSION,
        "format": "dstpu-universal",
        "mesh": mesh_dims,
        "axis_order": list(mesh_dims) if mesh_dims else None,
        "tree": skel(serialized),
    }
    if extra:
        layout.update({k: v for k, v in extra.items() if k not in layout})
    return layout


def write_layout(ckpt_path: str, state: Any,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build + atomically persist ``layout.json`` under ``ckpt_path``.
    Written BEFORE the integrity manifest so the manifest's file sizes
    cover it — a torn layout fails verification like any other file."""
    layout = build_layout(state, extra)
    atomic_write_text(os.path.join(ckpt_path, LAYOUT_FILE),
                      json.dumps(layout, indent=1, sort_keys=True))
    return layout


def read_layout(ckpt_path: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(ckpt_path, LAYOUT_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


# --------------------------------------------------------------------- #
# flattening / templates
# --------------------------------------------------------------------- #
def is_leaf_record(node: Any) -> bool:
    return isinstance(node, dict) and node.get(LEAF_KEY) == 1


def flat_records(tree: Any, prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """Skeleton -> {path: leaf record} (None nodes contribute nothing)."""
    out: Dict[str, Dict[str, Any]] = {}
    if is_leaf_record(tree):
        out[prefix] = tree
    elif isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flat_records(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif isinstance(tree, list):
        for i, v in enumerate(tree):
            out.update(flat_records(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    return out


def flat_values(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Serialized tree of live values -> {path: leaf}."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flat_values(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(flat_values(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    elif tree is not None:
        out[prefix] = tree
    return out


_PY_SCALARS = {"int": int, "float": float, "bool": bool, "str": str}


def template_from_layout(
    layout: Dict[str, Any],
    sharding_for: Callable[[str, Dict[str, Any]], Any],
    dtype_for: Optional[Callable[[str, Dict[str, Any]], Any]] = None,
    subtree: Optional[str] = None,
) -> Any:
    """Rebuild a restore template (ShapeDtypeStruct leaves carrying TARGET
    shardings) from the layout skeleton alone — no writing-job objects
    needed.  ``sharding_for(path, record)`` supplies each leaf's target
    sharding; ``dtype_for`` may override the stored dtype (tensorstore
    casts during the read).  ``subtree`` restricts the template to one
    top-level field (partial restore, e.g. params-only for serving) — the
    paths handed to the callbacks are then RELATIVE to that field, which
    is what spec trees keyed by param name expect."""
    import jax

    tree = layout["tree"]
    if subtree is not None:
        tree = tree[subtree]

    def build(node, prefix):
        if is_leaf_record(node):
            if node["shape"] is None:
                return _PY_SCALARS.get(node["dtype"], int)()
            dtype = np.dtype(dtype_for(prefix, node) if dtype_for is not None
                             else node["dtype"])
            return jax.ShapeDtypeStruct(tuple(node["shape"]), dtype,
                                        sharding=sharding_for(prefix, node))
        if isinstance(node, dict):
            return {k: build(v, f"{prefix}{SEP}{k}" if prefix else str(k))
                    for k, v in node.items()}
        if isinstance(node, list):
            return [build(v, f"{prefix}{SEP}{i}" if prefix else str(i))
                    for i, v in enumerate(node)]
        return None

    return build(tree, "")


def graft(target_serialized: Any, restored_serialized: Any) -> Tuple[Any, List[str]]:
    """Overlay restored leaves onto the target's serialized structure.

    Walks the TARGET structure (the resuming engine defines what exists);
    wherever the restored tree has a value at the same path, the restored
    value wins; target-only leaves keep their current value (that is how
    resettable buffers like ``grad_acc`` survive a source that never saved
    them).  Returns (merged tree, paths kept from the target)."""
    kept: List[str] = []

    def merge(tgt, src, prefix):
        if isinstance(tgt, dict):
            src = src if isinstance(src, dict) else {}
            return {k: merge(v, src.get(k),
                             f"{prefix}{SEP}{k}" if prefix else str(k))
                    for k, v in tgt.items()}
        if isinstance(tgt, list):
            src = src if isinstance(src, list) else []
            return [merge(v, src[i] if i < len(src) else None,
                          f"{prefix}{SEP}{i}" if prefix else str(i))
                    for i, v in enumerate(tgt)]
        if tgt is None:
            return None
        if src is None:
            kept.append(prefix)
            return tgt
        return src

    return merge(target_serialized, restored_serialized, ""), kept
