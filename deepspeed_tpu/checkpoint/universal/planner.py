"""Resharding planner: source layout × target mesh → per-leaf load plan.

Given the logical layout a checkpoint was written with (:mod:`.layout`) and
the sharded template the resuming job wants, classify every leaf:

  ``identical``   same slicing geometry — each target shard range-reads
                  exactly one source-shard-sized extent (a same-shape
                  restart, or a mesh whose ZeRO factors happen to agree);
  ``slice``       source replicated, target sharded — each target host
                  reads only its slice (shrink never gathers);
  ``gather``      source sharded, target replicated — every host reads the
                  full logical array (zero_stage lowered, or serving);
  ``reslice``     both sharded with different factors (grow/shrink/TP↔DP
                  re-split) — each target shard reads the covering source
                  ranges;
  ``replicated``  replicated on both sides.

The plan also carries a **per-host shard index**: for every target device,
the index ranges of the global array it will read, deduplicated per host —
the accounting that proves a reshard never materializes a full replica
unless the *target* layout is itself replicated.  Validation (shape/
structure divergence) happens here too, so a mismatched optimizer or model
fails with the exact diverging paths instead of an orbax tree error.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .layout import SEP, flat_records, flat_values, serialize_state

#: Top-level fields a resuming engine may legitimately re-initialize when the
#: source never saved them (and drop when the target has no use for them):
#: both are zero at every optimizer-step boundary, which is the only place a
#: checkpoint is ever written.
RESETTABLE_FIELDS = ("grad_acc", "comm_error")


class ReshardPlanError(RuntimeError):
    """Source checkpoint and target layout diverge in a way resharding
    cannot bridge (shape mismatch, missing non-resettable leaves)."""


def _entry_axes(entry: Any) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(str(a) for a in entry)
    return (str(entry),)


def _dim_factors(spec: Optional[List[Any]], mesh: Optional[Dict[str, int]],
                 ndim: int) -> Tuple[int, ...]:
    """Per-dimension shard counts implied by a (serialized) spec on a mesh."""
    factors = [1] * ndim
    if spec and mesh:
        for d, entry in enumerate(spec[:ndim]):
            for ax in _entry_axes(entry):
                factors[d] *= int(mesh.get(ax, 1))
    return tuple(factors)


def _spec_of_sharding(sharding: Any) -> Optional[List[Any]]:
    from .layout import _spec_to_json

    spec = getattr(sharding, "spec", None)
    return _spec_to_json(spec) if spec is not None else None


def _mesh_of_sharding(sharding: Any) -> Optional[Dict[str, int]]:
    shape = getattr(getattr(sharding, "mesh", None), "shape", None)
    return {str(k): int(v) for k, v in dict(shape).items()} if shape else None


@dataclasses.dataclass
class LeafPlan:
    path: str
    shape: Tuple[int, ...]
    src_dtype: str
    dst_dtype: str
    kind: str                      # identical|slice|gather|reslice|replicated
    src_factors: Tuple[int, ...]
    dst_factors: Tuple[int, ...]
    #: bytes of the global array (at source dtype)
    nbytes: int
    #: deduplicated bytes this process will read for the leaf
    read_bytes: int


@dataclasses.dataclass
class ReshardPlan:
    source_mesh: Optional[Dict[str, int]]
    target_mesh: Optional[Dict[str, int]]
    leaves: Dict[str, LeafPlan]
    #: source-only paths the target re-initializes (resettable fields)
    dropped: List[str]
    #: target-only paths kept at their current value (resettable fields)
    reset: List[str]
    errors: List[str]

    @property
    def reshaped(self) -> bool:
        """Does the load move any bytes differently than a same-mesh
        restart would?"""
        return (self.source_mesh or {}) != (self.target_mesh or {}) or \
            any(p.kind in ("slice", "gather", "reslice")
                for p in self.leaves.values())

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for p in self.leaves.values():
            out[p.kind] = out.get(p.kind, 0) + 1
        return out

    def total_read_bytes(self) -> int:
        return int(sum(p.read_bytes for p in self.leaves.values()))

    def summary(self) -> Dict[str, Any]:
        return {
            "reshaped": self.reshaped,
            "source_mesh": self.source_mesh,
            "target_mesh": self.target_mesh,
            "leaf_kinds": self.counts(),
            "read_bytes": self.total_read_bytes(),
            "logical_bytes": int(sum(p.nbytes for p in self.leaves.values())),
            "dropped": len(self.dropped),
            "reset": len(self.reset),
        }

    def raise_on_errors(self) -> None:
        if self.errors:
            head = "; ".join(self.errors[:8])
            more = f" (+{len(self.errors) - 8} more)" if len(self.errors) > 8 else ""
            raise ReshardPlanError(
                f"checkpoint cannot be resharded onto this job: {head}{more}")


def _local_read_bytes(sharding: Any, shape: Tuple[int, ...],
                      itemsize: int) -> int:
    """Deduplicated bytes THIS process reads for one leaf under the target
    sharding: the union of its addressable devices' index ranges.  Tensor-
    store reads exactly these ranges — a sharded target never pulls a full
    replica through any single host."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize
    if sharding is None:
        return nbytes
    try:
        index_map = sharding.addressable_devices_indices_map(tuple(shape))
    except (AttributeError, ValueError):
        return nbytes
    seen = set()
    total = 0
    for idx in index_map.values():
        key = tuple((s.start, s.stop, s.step) for s in idx) \
            if isinstance(idx, tuple) else idx
        if key in seen:
            continue
        seen.add(key)
        n = itemsize
        for dim, sl in zip(shape, idx if isinstance(idx, tuple) else ()):
            start, stop, _ = sl.indices(dim)
            n *= max(stop - start, 0)
        total += n
    return total


def _classify(src: Tuple[int, ...], dst: Tuple[int, ...]) -> str:
    src_sharded = any(f > 1 for f in src)
    dst_sharded = any(f > 1 for f in dst)
    if not src_sharded and not dst_sharded:
        return "replicated"
    if src == dst:
        return "identical"
    if not src_sharded:
        return "slice"
    if not dst_sharded:
        return "gather"
    return "reslice"


def plan_reshard(layout: Dict[str, Any], target_state: Any,
                 resettable: Tuple[str, ...] = RESETTABLE_FIELDS,
                 target_serialized: Any = None) -> ReshardPlan:
    """Map a saved layout onto a live target state pytree.

    ``target_state`` is the resuming job's state (arrays or
    ShapeDtypeStructs — only shape/dtype/sharding are consulted).
    ``target_serialized`` lets a caller that already serialized the target
    (the loader walks it for templates and grafting too) skip the repeat
    walk."""
    src_records = flat_records(layout["tree"])
    src_mesh = layout.get("mesh")
    if target_serialized is None:
        target_serialized = serialize_state(target_state)
    tgt_values = flat_values(target_serialized)
    tgt_mesh = None

    def is_resettable(path: str) -> bool:
        head = path.split(SEP, 1)[0]
        return head in resettable

    leaves: Dict[str, LeafPlan] = {}
    errors: List[str] = []
    dropped = [p for p in src_records if p not in tgt_values]
    reset = [p for p in tgt_values if p not in src_records]
    for p in dropped:
        if not is_resettable(p):
            errors.append(f"checkpoint leaf {p!r} has no home in the "
                          f"resuming job (optimizer/model changed?)")
    for p in reset:
        if not is_resettable(p):
            errors.append(f"resuming job needs leaf {p!r} the checkpoint "
                          f"never saved")

    for path, rec in src_records.items():
        tgt = tgt_values.get(path)
        if tgt is None or rec["shape"] is None:
            continue
        shape = tuple(rec["shape"])
        tgt_shape = tuple(getattr(tgt, "shape", ()) or ())
        if shape != tgt_shape:
            errors.append(f"{path}: global shape {list(shape)} in checkpoint "
                          f"vs {list(tgt_shape)} in the resuming job")
            continue
        sharding = getattr(tgt, "sharding", None)
        if tgt_mesh is None:
            tgt_mesh = _mesh_of_sharding(sharding)
        src_f = _dim_factors(rec.get("spec"), src_mesh, len(shape))
        dst_f = _dim_factors(_spec_of_sharding(sharding),
                             _mesh_of_sharding(sharding), len(shape))
        itemsize = np.dtype(rec["dtype"]).itemsize
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize
        kind = _classify(src_f, dst_f)
        leaves[path] = LeafPlan(
            path=path, shape=shape, src_dtype=rec["dtype"],
            dst_dtype=np.dtype(getattr(tgt, "dtype", rec["dtype"])).name,
            kind=kind, src_factors=src_f, dst_factors=dst_f, nbytes=nbytes,
            read_bytes=_local_read_bytes(sharding, shape, itemsize))

    return ReshardPlan(source_mesh=src_mesh, target_mesh=tgt_mesh,
                       leaves=leaves, dropped=dropped, reset=reset,
                       errors=errors)
