"""Mesh-shape-agnostic checkpoint restore.

The load-side half of universal checkpoints: rebuild a restore template
from the saved layout manifest (:mod:`.layout`), plan the reshard
(:mod:`.planner`), and let tensorstore range-read only the bytes each
target shard needs — params and optimizer state land on the resuming
job's mesh directly, whatever mesh wrote them (chips added or removed,
zero_stage changed, TP↔DP↔SP re-split).

Fault semantics match PR-1 checkpoints exactly: every candidate tag is
verified against its integrity manifest before any byte is trusted, and
when the newest tag is torn — including a *source shard deleted between
commit and resharded load* (``DSTPU_FAULT_INJECT`` ``shard_missing``) —
the loader degrades to the newest valid older committed tag instead of
crashing, counting the incident (``reshard/fallbacks``).
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...runtime.fault import injection
from ...runtime.fault.manifest import (CheckpointCorruptError, STATE_DIR,
                                       verify_checkpoint)
from ...runtime.fault.retry import record_fault_event, retryable
from ...telemetry import emit_event
from ...utils.logging import logger
from . import layout as L
from .planner import ReshardPlan, ReshardPlanError, plan_reshard

META_FILE = "meta.json"


class NoLayoutError(RuntimeError):
    """The checkpoint predates the universal format (no ``layout.json``);
    callers fall back to the template-structure load path."""


def _read_meta(path: str) -> Dict[str, Any]:
    p = os.path.join(path, META_FILE)
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        return json.load(f)


def _device_resident(tree: Any) -> Any:
    """Orbax restores land on host memory kind; re-commit each leaf to its
    sharding's device memory so downstream jit sees ordinary device
    arrays."""
    import jax

    def fix(x):
        sh = getattr(x, "sharding", None)
        if sh is None:
            return x
        try:
            return jax.device_put(x, sh.with_memory_kind("device"))
        except (AttributeError, ValueError, TypeError):
            return jax.device_put(x, sh)

    return jax.tree.map(fix, tree)


def _single_device_sharding():
    """Somewhere to park source-only leaves that will be dropped after the
    graft — one local device, never a full-mesh replica."""
    import jax

    return jax.sharding.SingleDeviceSharding(jax.local_devices()[0])


@retryable("ckpt_reshard_restore")
def _restore(state_path: str, template: Any, transforms: Optional[dict] = None):
    import orbax.checkpoint as ocp

    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    kwargs = {}
    if transforms is not None:
        kwargs["transforms"] = transforms
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(state_path, item=template,
                             restore_args=restore_args, **kwargs)


def _candidate_tags(store, tag: Optional[str]) -> Tuple[List[str], bool]:
    """(ordered candidates, fallback allowed).  An explicit tag is an
    explicit trust decision — corrupt means raise, exactly like
    ``OrbaxCheckpointEngine.load``.  ``tag=None`` resumes: newest committed
    first, then older committed tags (newest first)."""
    if tag is not None:
        return [str(tag)], False
    first = store.latest_tag()
    if first is None:
        return [], True
    seen = {first}
    out = [first]
    for t in reversed(store.committed_tags()):
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out, True


def load_state_resharded(
    store,
    target_state: Any,
    tag: Optional[str] = None,
    resettable: Tuple[str, ...] = None,
) -> Tuple[str, Any, Dict[str, Any], ReshardPlan]:
    """Restore ``store``'s checkpoint onto the layout of ``target_state``.

    ``store`` is an :class:`~...runtime.checkpoint_engine.
    orbax_checkpoint_engine.OrbaxCheckpointEngine`; ``target_state`` the
    resuming job's live state pytree (its shardings define the target
    layout).  Returns ``(tag, state, meta, plan)`` with ``state`` already
    sharded for the target mesh.  Raises :class:`NoLayoutError` for
    pre-universal checkpoints and :class:`CheckpointCorruptError` when no
    loadable candidate remains.
    """
    from orbax.checkpoint import utils as ou

    from .planner import RESETTABLE_FIELDS
    if resettable is None:
        resettable = RESETTABLE_FIELDS

    candidates, fallback = _candidate_tags(store, tag)
    if not candidates:
        raise CheckpointCorruptError(
            f"{store.ckpt_dir}: no loadable checkpoint tag")

    # one serialization walk of the (possibly huge) target tree, shared by
    # the plan, the template shardings, and the graft — and by every
    # fallback candidate
    tgt_serialized = L.serialize_state(target_state)
    tgt_flat = L.flat_values(tgt_serialized)
    park = _single_device_sharding()

    last_err: Optional[Exception] = None
    for i, cand in enumerate(candidates):
        path = store._path(cand)
        try:
            # the resharded load is the one moment a deleted source shard
            # can hurt a *different-shape* job; the injection site lives
            # here so tests can tear exactly this window
            injection.inject("reshard_load",
                             path=os.path.join(path, STATE_DIR))
            if store.verify:
                # cold verification: the store's cache reflects what it saw
                # at latest_tag() time, not what is on disk NOW
                verify_checkpoint(path, require_manifest=(i > 0))
            lay = L.read_layout(path)
            if lay is None:
                raise NoLayoutError(
                    f"{path}: no layout manifest (pre-universal checkpoint)")

            plan = plan_reshard(lay, target_state, resettable=resettable,
                                target_serialized=tgt_serialized)
            plan.raise_on_errors()

            def sharding_for(p, rec):
                leaf = tgt_flat.get(p)
                sh = getattr(leaf, "sharding", None)
                return sh if sh is not None else park

            def dtype_for(p, rec):
                leaf = tgt_flat.get(p)
                return getattr(leaf, "dtype", None) or rec["dtype"]

            template = L.template_from_layout(lay, sharding_for, dtype_for)
            # top-level fields the target has no leaves for (e.g. a gas>1
            # source's grad_acc resuming into gas=1) would be read in full
            # just to be discarded at graft time — prune them and switch to
            # orbax's partial restore so their bytes never leave disk
            transforms = None
            if isinstance(template, dict):
                src_tops = {p.split(L.SEP, 1)[0]
                            for p, r in L.flat_records(lay["tree"]).items()
                            if r["shape"] is not None}
                tgt_tops = {p.split(L.SEP, 1)[0] for p in tgt_flat}
                for key in src_tops - tgt_tops:
                    template.pop(key, None)
                    transforms = {}
            restored = _restore(os.path.join(path, STATE_DIR), template,
                                transforms=transforms)
            merged, kept = L.graft(tgt_serialized, restored)
            state = ou.deserialize_tree(merged, target_state,
                                        keep_empty_nodes=True)
            if plan.dropped:
                logger.info(f"reshard load {path}: dropped source-only "
                            f"leaves {plan.dropped}")
            if kept:
                logger.info(f"reshard load {path}: re-initialized "
                            f"target-only leaves {kept}")
            return cand, state, _read_meta(path), plan
        except NoLayoutError:
            raise
        except ReshardPlanError:
            raise
        except CheckpointCorruptError as e:
            last_err = e
            if not fallback:
                raise
            record_fault_event("reshard/fallbacks")
            emit_event("checkpoint_reshard_fallback", tag=str(cand),
                       dir=store.ckpt_dir, error=str(e)[:300])
            logger.warning(f"resharded load of {path} failed verification "
                           f"({e}); falling back to an older committed tag")
    raise last_err if last_err is not None else CheckpointCorruptError(
        f"{store.ckpt_dir}: no valid checkpoint to reshard from")


def load_params_resharded(
    ckpt_dir: str,
    tag: Optional[str] = None,
    sharding_for: Optional[Callable[[str, Dict[str, Any]], Any]] = None,
    dtype: Any = None,
    fault_config: Any = None,
    params_field: str = "params",
) -> Tuple[str, Any, Dict[str, Any]]:
    """Partial restore of the parameter subtree only — the train→serve
    handoff.  A serving job knows nothing of the training optimizer; the
    layout manifest supplies the params skeleton and orbax's partial
    restore never touches the optimizer-state bytes.  ``sharding_for``
    places each leaf on the inference mesh (default: fully replicated on
    the current global mesh); ``dtype`` casts during the read (fp32 master
    → bf16 serving).  Returns ``(tag, params, layout)``.
    """
    from orbax.checkpoint import utils as ou

    from ...runtime.checkpoint_engine.orbax_checkpoint_engine import \
        OrbaxCheckpointEngine

    store = OrbaxCheckpointEngine(ckpt_dir, fault_config=fault_config)
    candidates, fallback = _candidate_tags(store, tag)
    if not candidates:
        raise CheckpointCorruptError(f"{ckpt_dir}: no loadable checkpoint tag")

    if sharding_for is None:
        from ...runtime.topology import get_topology

        replicated = get_topology().replicated()

        def sharding_for(p, rec):  # noqa: F811 — default placement
            return replicated

    last_err: Optional[Exception] = None
    for i, cand in enumerate(candidates):
        path = store._path(cand)
        try:
            injection.inject("reshard_load",
                             path=os.path.join(path, STATE_DIR))
            if store.verify:
                verify_checkpoint(path, require_manifest=(i > 0))
            lay = L.read_layout(path)
            if lay is None:
                raise NoLayoutError(
                    f"{path}: no layout manifest (pre-universal checkpoint)")
            if params_field not in lay["tree"]:
                raise ReshardPlanError(
                    f"{path}: layout has no {params_field!r} subtree")

            def dtype_for(p, rec):
                return dtype if dtype is not None else rec["dtype"]

            sub = L.template_from_layout(lay, sharding_for, dtype_for,
                                         subtree=params_field)
            template = {params_field: sub}
            restored = _restore(os.path.join(path, STATE_DIR), template,
                                transforms={})
            # deserialize back through the subtree skeleton so list nodes
            # (tuple params containers) regain their saved form
            params = ou.deserialize_tree(restored[params_field], sub,
                                         keep_empty_nodes=True)
            return cand, _device_resident(params), lay
        except (NoLayoutError, ReshardPlanError):
            raise
        except CheckpointCorruptError as e:
            last_err = e
            if not fallback:
                raise
            record_fault_event("reshard/fallbacks")
            emit_event("checkpoint_reshard_fallback", tag=str(cand),
                       dir=ckpt_dir, error=str(e)[:300])
            logger.warning(f"params reshard load of {path} failed "
                           f"({e}); falling back to an older committed tag")
    raise last_err if last_err is not None else CheckpointCorruptError(
        f"{ckpt_dir}: no valid checkpoint to reshard from")
