"""Universal (mesh-shape-agnostic) checkpoints.

Save side (:mod:`.layout`): the orbax checkpoint engine writes a logical
layout manifest — every param/optimizer leaf's global shape, dtype, and
partition spec plus the writing mesh — alongside the PR-1 integrity
manifest.  Load side (:mod:`.planner` + :mod:`.loader`): a resharding
planner maps saved shards onto ANY target mesh and the loader range-reads
only the bytes each target shard needs, with torn/partial sources falling
back to the newest valid tag exactly like same-mesh checkpoints do.
"""
from .layout import (LAYOUT_FILE, build_layout, read_layout,  # noqa: F401
                     write_layout)
from .loader import (NoLayoutError, load_params_resharded,  # noqa: F401
                     load_state_resharded)
from .planner import (LeafPlan, ReshardPlan, ReshardPlanError,  # noqa: F401
                      plan_reshard)
