"""Ulysses sequence parallelism (reference: deepspeed/sequence/layer.py:257,311).

``DistributedAttention`` runs any local attention function under sequence
parallelism: tokens are sharded over the "seq" mesh axis; before attention,
an all-to-all scatters *heads* and gathers *sequence* (each rank then holds
full sequences for H/sp heads), attention runs locally, and the inverse
all-to-all restores the [B, S/sp, H, hd] layout.

The reference implements this with torch.distributed all_to_all_single +
manual permutes (``_SeqAllToAll``); here it is a ``shard_map`` region over the
mesh with ``jax.lax.all_to_all``, so it composes with jit/GSPMD and autodiff
(all_to_all's transpose is the inverse all-to-all — no custom autograd fn
needed, unlike the reference).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import DATA, DATA_OUTER, EXPERT, SEQ, get_topology


def _seq_all_to_all(x, scatter_heads: bool):
    """[B, s, H, hd] -> [B, S, H/sp, hd] (scatter_heads) or inverse."""
    if scatter_heads:
        # split head dim across seq group, concat along sequence dim
        return jax.lax.all_to_all(x, SEQ, split_axis=2, concat_axis=1, tiled=True)
    return jax.lax.all_to_all(x, SEQ, split_axis=1, concat_axis=2, tiled=True)


class DistributedAttention:
    """Reference: sequence/layer.py:311.

    Parameters
    ----------
    local_attention: f(q, k, v, **kw) -> out over [B, S, H_local, hd].
    sp_axis: mesh axis name carrying the sequence shards.
    """

    def __init__(self, local_attention: Callable, sp_axis: str = SEQ,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.sp_axis = sp_axis
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx
        self._jit_cache = {}

    def __call__(self, query, key, value, *args, **kwargs):
        topo = get_topology()
        sp = topo.dims.get(self.sp_axis, 1)
        if sp <= 1:
            return self.local_attn(query, key, value, *args, **kwargs)

        n_heads = query.shape[2]
        if n_heads % sp != 0:
            raise ValueError(
                f"Ulysses requires heads ({n_heads}) divisible by sp ({sp}); "
                f"uneven-head support: pad heads or use ring attention")

        from ..runtime.topology import shard_map_context

        def body(q, k, v):
            q = _seq_all_to_all(q, scatter_heads=True)
            k = _seq_all_to_all(k, scatter_heads=True)
            v = _seq_all_to_all(v, scatter_heads=True)
            out = self.local_attn(q, k, v, *args, **kwargs)
            return _seq_all_to_all(out, scatter_heads=False)

        mesh, already_manual = shard_map_context(topo)
        if self.sp_axis in already_manual:
            # Enclosing shard_map is already manual over the seq axis (e.g.
            # the pipeline engine's tick loop): collectives resolve there.
            return body(query, key, value)
        # PARTIAL-manual over the seq axis only: batch/data sharding rides
        # GSPMD, so this nests inside manual-over-data regions (explicit-comm
        # train step) and composes with any outer jit.  The jit wrapper keeps
        # the eager call path working (partial-manual shard_map requires a
        # tracing context on this jax version); inside an enclosing jit it
        # simply inlines.
        io_spec = P(None, self.sp_axis, None, None)
        # cache the jitted wrapper: a fresh closure per call would defeat
        # jit's identity-keyed cache and recompile every eager invocation
        try:
            cache_key = (mesh, tuple(args), tuple(sorted(kwargs.items())))
            fn = self._jit_cache.get(cache_key)
        except TypeError:           # unhashable extra args: don't cache
            cache_key, fn = None, None
        if fn is None:
            from ..runtime.topology import compat_shard_map

            fn = jax.jit(compat_shard_map(
                body, mesh=mesh, in_specs=(io_spec, io_spec, io_spec),
                out_specs=io_spec, manual_axes={self.sp_axis}))
            if cache_key is not None:
                self._jit_cache[cache_key] = fn
        return fn(query, key, value)


class UlyssesAttention(DistributedAttention):
    """Convenience: Ulysses over the framework's XLA/flash local attention."""

    def __init__(self, cfg=None, sp_axis: str = SEQ):
        from ..models.transformer import _xla_attention

        def local(q, k, v, causal=True):
            from ..accelerator import get_accelerator

            if cfg is not None and getattr(cfg, "use_flash", False) and \
                    get_accelerator().supports_pallas() and q.shape[1] >= 128:
                from ..ops.transformer.flash_attention import flash_attention

                return flash_attention(q, k, v, causal=causal)
            return _xla_attention(q, k, v, causal=causal)

        super().__init__(local, sp_axis)
