"""Ring attention — the TPU-native context-parallel (CP) strategy.

The reference has no ring attention (SURVEY §2.2: its long-context story is
Ulysses all-to-all + FPDT chunk/offload, fpdt_layer.py:510,971).  On TPU, ICI
neighbor links make a kv-rotation ring the natural long-context primitive, so
this framework adds it as the CP path alongside Ulysses.

Mechanics: sequence sharded over the "seq" axis.  Each rank keeps its query
shard; key/value shards rotate around the ring via ``lax.ppermute``.  Per-step
partial attention produces (out, lse) which are merged with the numerically
stable online-softmax rule — the same merge FPDT uses for its chunks
(reference fpdt_layer.py:40-78).  Causality at chunk granularity: a rank
attends fully to earlier chunks, causally to its own, not at all to later
ones (those steps are skipped via masking).

Differentiable by construction (scan + ppermute transpose = reverse ring).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import DATA, EXPERT, SEQ, get_topology

_NEG_INF = -1e30
_ring_jit_cache: dict = {}


def _chunk_attn(q, k, v, scale, mask):
    """Partial attention over one kv chunk → (unnormalized out, m, l).

    q [B,s,H,hd], k/v [B,c,H,hd], mask [s, c] or None.
    Returns out [B,s,H,hd] (sum of exp(s - m) * v), m and l [B,s,H].
    """
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask[None, :, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,s,H]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(m)[..., None], p, 0.0)         # fully-masked rows
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out, m, l


def _merge(acc, out, m_acc, m, l_acc, l):
    """Online-softmax merge of two partial results (FPDT-style)."""
    m_new = jnp.maximum(m_acc, m)
    a1 = jnp.exp(m_acc - m_new)
    a2 = jnp.exp(m - m_new)
    acc = acc * a1[..., None] + out * a2[..., None]
    l_new = l_acc * a1 + l * a2
    return acc, m_new, l_new


def ring_attention(query, key, value, causal: bool = True,
                   scale: Optional[float] = None, sp_axis: str = SEQ):
    """Context-parallel attention over [B, S, H, hd] with S sharded on sp_axis.

    GQA is supported (kv heads broadcast before the ring).
    """
    topo = get_topology()
    sp = topo.dims.get(sp_axis, 1)
    hd = query.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    H, KV = query.shape[2], key.shape[2]
    if KV != H:
        key = jnp.repeat(key, H // KV, axis=2)
        value = jnp.repeat(value, H // KV, axis=2)
    if sp <= 1:
        out, m, l = _chunk_attn(query, key, value, scale,
                                _local_causal_mask(query.shape[1], key.shape[1])
                                if causal else None)
        return (out / jnp.maximum(l, 1e-30)[..., None]).astype(query.dtype)

    from ..runtime.topology import shard_map_context

    perm = [(i, (i + 1) % sp) for i in range(sp)]  # kv moves to next rank

    def body(q, k, v):
        r = jax.lax.axis_index(sp_axis)
        s_local = q.shape[1]
        B, _, H_, hd_ = q.shape
        acc = jnp.zeros((B, s_local, H_, hd_), jnp.float32)
        m_acc = jnp.full((B, s_local, H_), _NEG_INF, jnp.float32)
        l_acc = jnp.zeros((B, s_local, H_), jnp.float32)

        def step(t, carry):
            acc, m_acc, l_acc, k_t, v_t = carry
            chunk = (r - t) % sp  # which sequence chunk we currently hold
            if causal:
                # chunk < r: attend fully; == r: local causal; > r: skip.
                local_mask = _local_causal_mask(s_local, s_local)
                full = jnp.ones((s_local, s_local), bool)
                none = jnp.zeros((s_local, s_local), bool)
                mask = jnp.where(chunk < r, full,
                                 jnp.where(chunk == r, local_mask, none))
            else:
                mask = None
            out, m, l = _chunk_attn(q, k_t, v_t, scale, mask)
            acc, m_acc, l_acc = _merge(acc, out, m_acc, m, l_acc, l)
            k_t = jax.lax.ppermute(k_t, sp_axis, perm)
            v_t = jax.lax.ppermute(v_t, sp_axis, perm)
            return acc, m_acc, l_acc, k_t, v_t

        acc, m_acc, l_acc, _, _ = jax.lax.fori_loop(
            0, sp, step, (acc, m_acc, l_acc, k, v))
        out = acc / jnp.maximum(l_acc, 1e-30)[..., None]
        return out.astype(q.dtype)

    mesh, already_manual = shard_map_context(topo)
    if sp_axis in already_manual:
        return body(query, key, value)
    # Partial-manual over the ring axis only (see layer.py): data/batch
    # sharding stays GSPMD so the ring nests inside manual-over-data regions.
    # jit keeps the eager call path working (inlines under an enclosing jit);
    # the wrapper is cached so eager loops don't recompile per call.
    io_spec = P(None, sp_axis, None, None)
    cache_key = (mesh, sp_axis, causal, float(scale), sp)
    fn = _ring_jit_cache.get(cache_key)
    if fn is None:
        from ..runtime.topology import compat_shard_map

        fn = jax.jit(compat_shard_map(
            body, mesh=mesh, in_specs=(io_spec, io_spec, io_spec),
            out_specs=io_spec, manual_axes={sp_axis}))
        _ring_jit_cache[cache_key] = fn
    return fn(query, key, value)


def _local_causal_mask(sq, sk):
    qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return qi >= ki
