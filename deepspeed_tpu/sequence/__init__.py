from .cross_entropy import vocab_sequence_parallel_cross_entropy
from .layer import DistributedAttention, UlyssesAttention
from .ring_attention import ring_attention

__all__ = [
    "DistributedAttention",
    "UlyssesAttention",
    "ring_attention",
    "vocab_sequence_parallel_cross_entropy",
]
