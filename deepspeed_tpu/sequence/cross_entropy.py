"""Sequence-parallel cross entropy (reference: deepspeed/sequence/cross_entropy.py:11).

With tokens sharded over the "seq" axis, each shard computes its local
token losses; the global mean reduces over (seq × data) with valid-token
weighting.  Runs inside jit/shard_map; under pure GSPMD sharding the psum is
inserted by XLA, so this explicit version is only needed in shard_map regions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..runtime.topology import SEQ, get_topology


def vocab_sequence_parallel_cross_entropy(logits, labels, sp_axis: str = SEQ,
                                          ignore_index: int = -100):
    """logits [B, s_local, V] (f32 recommended), labels [B, s_local].

    Returns the global mean NLL over valid tokens across the whole sequence
    group.  Must run where ``sp_axis`` is bound (shard_map) — or with sp=1 it
    degrades to plain masked cross entropy.
    """
    topo = get_topology()
    sp = topo.dims.get(sp_axis, 1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    tok = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    local_sum = -jnp.sum(tok * valid)
    local_cnt = jnp.sum(valid).astype(jnp.float32)
    if sp > 1:
        local_sum = jax.lax.psum(local_sum, sp_axis)
        local_cnt = jax.lax.psum(local_cnt, sp_axis)
    return local_sum / jnp.maximum(local_cnt, 1.0)
