"""FPDT / Ulysses-Offload: chunked long-context attention with host offload.

Reference: ``deepspeed/sequence/fpdt_layer.py`` — online-softmax chunk merge
(:40-78), double-buffered host-offloaded KV chunks (SequenceChunk :462,
_FPDTGPUOffloadingAttentionImpl_ :510), chunked MLP (:1056) and chunked logits
loss (:1137); enables 2M-token contexts on 4 GPUs.

TPU design: queries are processed in chunks with ``lax.scan``; the KV history
a chunk attends to is accumulated K/V stacked per chunk.  With
``offload=True`` the KV history lives in pinned host memory
(``jax.device_put`` with host memory-kind sharding) and each scan step fetches
one chunk back — HBM holds only O(chunk) KV, giving the reference's
memory-vs-bandwidth trade on TPU (host DMA instead of cudaMemcpyAsync).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.transformer import _xla_attention


def _chunk_partials(q, k, v, scale, mask):
    """(unnormalized out, rowmax m, rowsum l) for one q-chunk vs one kv-chunk."""
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask[None, :, None, :], s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(m)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out, m, l


def _merge(acc, m_acc, l_acc, out, m, l):
    """FPDT online-softmax merge (reference :40-78)."""
    m_new = jnp.maximum(m_acc, m)
    a1 = jnp.exp(m_acc - m_new)
    a2 = jnp.exp(m - m_new)
    return acc * a1[..., None] + out * a2[..., None], m_new, l_acc * a1 + l * a2


def chunked_attention(q, k, v, chunk_size: int, causal: bool = True,
                      scale: Optional[float] = None,
                      offload: bool = False,
                      remat: bool = True) -> jnp.ndarray:
    """Attention over [B, S, H, hd] computed q-chunk × kv-chunk with O(S·c)
    peak score memory instead of O(S²).

    ``offload=True`` parks the K/V history in host memory and streams chunks
    back per step (Ulysses-Offload's double-buffered host KV).

    ``remat=True`` (default) checkpoints each kv-step so the BACKWARD pass
    refetches chunks instead of keeping autodiff residuals of every fetched
    K/V chunk alive — without it, reverse-mode through the scan would
    re-materialize the entire KV history in device memory, defeating the
    offload (reference fpdt_layer.py:510 streams chunks in backward too;
    verified by the peak-memory test in tests/unit/test_fpdt_memory.py).
    """
    B, S, H, hd = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    assert S % chunk_size == 0, "S must divide by chunk_size (pad upstream)"
    n = S // chunk_size

    kc = k.reshape(B, n, chunk_size, H, hd).transpose(1, 0, 2, 3, 4)  # [n,B,c,H,hd]
    vc = v.reshape(B, n, chunk_size, H, hd).transpose(1, 0, 2, 3, 4)
    if offload:
        host = _host_device()
        if host is not None:
            kc = jax.device_put(kc, host)
            vc = jax.device_put(vc, host)

    qc = q.reshape(B, n, chunk_size, H, hd).transpose(1, 0, 2, 3, 4)

    qi = jax.lax.broadcasted_iota(jnp.int32, (chunk_size, chunk_size), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (chunk_size, chunk_size), 1)
    diag_mask = qi >= ki

    def q_chunk_body(qi_idx, q_chunk):
        acc = jnp.zeros((B, chunk_size, H, hd), jnp.float32)
        m_acc = jnp.full((B, chunk_size, H), -1e30, jnp.float32)
        l_acc = jnp.zeros((B, chunk_size, H), jnp.float32)

        def kv_step(carry, ki_idx):
            acc, m_acc, l_acc = carry
            # dynamic_index of a pinned_host-resident array + explicit
            # Space.Device transfer = a host→device DMA of exactly one chunk
            # (the double-buffered fetch); compute ops must see device memory.
            k_t = jax.lax.dynamic_index_in_dim(kc, ki_idx, 0, keepdims=False)
            v_t = jax.lax.dynamic_index_in_dim(vc, ki_idx, 0, keepdims=False)
            if offload:
                from jax.memory import Space

                k_t = jax.device_put(k_t, Space.Device)
                v_t = jax.device_put(v_t, Space.Device)
            if causal:
                mask = jnp.where(ki_idx < qi_idx,
                                 jnp.ones_like(diag_mask),
                                 jnp.where(ki_idx == qi_idx, diag_mask,
                                           jnp.zeros_like(diag_mask)))
            else:
                mask = None
            out, m, l = _chunk_partials(q_chunk, k_t, v_t, scale, mask)
            acc, m_acc, l_acc = _merge(acc, m_acc, l_acc, out, m, l)
            return (acc, m_acc, l_acc), None

        body = jax.checkpoint(kv_step) if remat else kv_step
        (acc, m_acc, l_acc), _ = jax.lax.scan(
            body, (acc, m_acc, l_acc), jnp.arange(n))
        return (acc / jnp.maximum(l_acc, 1e-30)[..., None]).astype(q.dtype)

    outs = jax.lax.map(lambda args: q_chunk_body(*args),
                       (jnp.arange(n), qc))
    return outs.transpose(1, 0, 2, 3, 4).reshape(B, S, H, hd)


def _host_device():
    """Pinned-host sharding for KV parking (None if unsupported)."""
    try:
        import jax

        dev = jax.devices()[0]
        from jax.sharding import SingleDeviceSharding

        return SingleDeviceSharding(dev, memory_kind="pinned_host")
    except Exception:
        return None


def chunked_mlp(mlp_fn, x: jnp.ndarray, chunk_size: int) -> jnp.ndarray:
    """Chunked FFN (reference :1056): process [B, S, D] sequence-chunk-wise."""
    B, S, D = x.shape
    assert S % chunk_size == 0
    n = S // chunk_size
    xc = x.reshape(B, n, chunk_size, D).transpose(1, 0, 2, 3)
    out = jax.lax.map(mlp_fn, xc)
    return out.transpose(1, 0, 2, 3).reshape(B, S, -1)


def chunked_lm_loss(hidden: jnp.ndarray, labels: jnp.ndarray,
                    lm_head: jnp.ndarray, chunk_size: int,
                    ignore_index: int = -100) -> jnp.ndarray:
    """Chunked logits+loss (reference :1137): never materializes [B, S, V]."""
    B, S, D = hidden.shape
    assert S % chunk_size == 0
    n = S // chunk_size
    hc = hidden.reshape(B, n, chunk_size, D).transpose(1, 0, 2, 3)
    lc = labels.reshape(B, n, chunk_size).transpose(1, 0, 2)

    def chunk_loss(args):
        h, lab = args
        logits = (h @ lm_head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        tok = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(-tok * valid), jnp.sum(valid)

    sums, counts = jax.lax.map(chunk_loss, (hc, lc))
    return jnp.sum(sums) / jnp.maximum(jnp.sum(counts), 1)


class FPDT_Attention:
    """Reference class name (fpdt_layer.py:971)."""

    def __init__(self, chunk_size: int = 1024, causal: bool = True,
                 offload: bool = True):
        self.chunk_size = chunk_size
        self.causal = causal
        self.offload = offload

    def __call__(self, q, k, v):
        return chunked_attention(q, k, v, self.chunk_size, self.causal,
                                 offload=self.offload)
