"""deepspeed_tpu: a TPU-native large-scale training & inference framework.

Provides the capabilities of the DeepSpeed reference framework
(`deepspeed/__init__.py:69,268,291,369`), re-designed for JAX/XLA/Pallas on
TPU device meshes: ZeRO via sharding, pipeline/tensor/expert/sequence
parallelism over a named mesh, Pallas kernels for the hot ops, and a
ragged-batching inference engine.
"""
from __future__ import annotations

from typing import Any, Optional

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .runtime import zero  # noqa: F401
from .runtime.config import DeepSpeedConfig  # noqa: F401
from .runtime.topology import TopologyConfig, initialize_mesh  # noqa: F401


def initialize(
    args: Any = None,
    model: Any = None,
    optimizer: Any = None,
    model_parameters: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    distributed_port: Optional[int] = None,
    mpu: Any = None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Any = None,
    config: Any = None,
    config_params: Any = None,
    topology: Any = None,
    mesh_config: Optional["TopologyConfig"] = None,
    seed: int = 0,
):
    """Create a training engine (reference: ``deepspeed.initialize``,
    deepspeed/__init__.py:69).

    Returns ``(engine, optimizer, dataloader, lr_scheduler)`` like the
    reference.  ``model`` is a loss callable ``f(params, batch, rng) -> loss``
    or a flax module; ``model_parameters`` is the initial parameter pytree.
    """
    import importlib.util
    import json

    from .runtime.engine import DeepSpeedEngine

    config = config if config is not None else config_params
    if args is not None and getattr(args, "deepspeed_config", None):
        if config is not None:
            raise ValueError(
                "Not sure how to proceed: both args.deepspeed_config and the "
                "config argument were given (reference semantics: pass one)")
        config = args.deepspeed_config

    # Normalize to a dict once (DeepSpeedConfig instances keep their raw dict).
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    raw_cfg = config.raw if isinstance(config, DeepSpeedConfig) else (config or {})

    # Overlap's latency-hiding-scheduler flags must land in the environment
    # BEFORE the first backend touch (libtpu reads LIBTPU_INIT_ARGS once at
    # client init) — i.e. before init_distributed/mesh building below.
    # Safe no-op on CPU and when the block doesn't ask for flags.
    from .runtime.overlap.xla_flags import configure_from_raw

    configure_from_raw(raw_cfg)

    if dist_init_required is None or dist_init_required:
        comm.init_distributed(distributed_port=distributed_port)

    if topology is None and mpu is not None:
        # Megatron-style mpu object (reference: engine honors
        # mpu.get_*_parallel_group(); here we honor the sizes).
        tp = getattr(mpu, "get_tensor_model_parallel_world_size",
                     getattr(mpu, "get_model_parallel_world_size", lambda: 1))()
        pp = getattr(mpu, "get_pipeline_model_parallel_world_size", lambda: 1)()
        topology = initialize_mesh(TopologyConfig(tensor=tp, pipe=pp), force=True)

    if topology is None:
        if mesh_config is not None:
            topology = initialize_mesh(mesh_config, force=True)
        else:
            topology = _topology_from_env_or_config(raw_cfg)

    if isinstance(config, DeepSpeedConfig):
        ds_config = config
        if ds_config._topology is not topology:
            # Re-resolve batch sizes against the actual mesh.
            ds_config = DeepSpeedConfig(ds_config.raw, topology=topology)
    else:
        ds_config = DeepSpeedConfig(config, topology=topology)

    engine_cls = DeepSpeedEngine
    from .runtime.pipe.module import PipelinedCausalLM, PipelineModule

    if isinstance(model, (PipelineModule, PipelinedCausalLM)):
        from .runtime.pipe.engine import PipelineEngine

        engine_cls = PipelineEngine

    engine = engine_cls(
        model=model, config=ds_config, topology=topology,
        model_parameters=model_parameters, optimizer=optimizer,
        lr_scheduler=lr_scheduler, training_data=training_data,
        collate_fn=collate_fn, seed=seed)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def _topology_from_env_or_config(cfg: dict):
    """The elastic agent's re-planned mesh wins over config-derived degrees.

    A worker restarted with ``--allow-reshape`` carries the gang's actual
    capacity in ``DSTPU_ELASTIC_MESH_SHAPE`` — the DeepSpeed config still
    describes the LAUNCH-time world, so building from it would reconstruct
    the stale pre-shrink mesh (or fail outright on fewer chips).  Explicit
    ``topology=``/``mesh_config=``/``mpu=`` arguments still take precedence
    over both (the caller hand-wired a mesh on purpose)."""
    from .runtime.topology import topology_config_from_env
    from .utils.logging import log_dist

    env_cfg = topology_config_from_env()
    if env_cfg is None:
        return _topology_from_config(cfg)
    import jax
    import numpy as np

    devices = jax.devices()
    explicit = [env_cfg.pipe, env_cfg.data, env_cfg.expert, env_cfg.seq,
                env_cfg.tensor]
    if all(d > 0 for d in explicit):
        # the re-planned gang may be smaller than this host's visible pool
        # (CPU sim; or a worker seeing the full host while the agent planned
        # a subset): take the leading devices the plan needs
        needed = int(np.prod(explicit))
        if needed < len(devices):
            devices = devices[:needed]
    log_dist(f"elastic reshape: building mesh from DSTPU_ELASTIC_MESH_SHAPE "
             f"({env_cfg}) over {len(devices)} device(s); config-derived "
             f"parallel degrees are superseded for this incarnation",
             ranks=[0])
    return initialize_mesh(env_cfg, devices=devices, force=True)


def _topology_from_config(cfg: dict):
    """Derive mesh degrees from DeepSpeed config keys (sequence_parallel_size,
    tensor_parallel.autotp_size, pipeline.stages, moe ep_size)."""
    from .runtime.topology import get_topology

    tp = cfg.get("tensor_parallel", {}).get("autotp_size") or \
        cfg.get("tensor_parallel", {}).get("tp_size") or 1
    sp = cfg.get("sequence_parallel_size", 1)
    pp = cfg.get("pipeline", {}).get("stages", 1)
    ep = cfg.get("moe", {}).get("ep_size", 1)
    if tp == 1 and sp == 1 and pp == 1 and ep == 1:
        return get_topology()
    return initialize_mesh(
        TopologyConfig(pipe=pp, tensor=tp, seq=sp, expert=ep), force=True)


def init_distributed(dist_backend: str = "xla", **kwargs) -> None:
    """Reference: deepspeed/__init__.py:268 → comm.init_distributed."""
    comm.init_distributed(dist_backend=dist_backend, **kwargs)


def init_inference(model: Any = None, config: Any = None, **kwargs):
    """Create an inference engine (reference: deepspeed/__init__.py:291)."""
    import importlib.util

    if importlib.util.find_spec("deepspeed_tpu.inference.engine") is None:
        raise NotImplementedError(
            "deepspeed_tpu.inference is not available in this build")
    from .inference.engine import InferenceEngine

    return InferenceEngine(model=model, config=config, **kwargs)


def add_config_arguments(parser):
    """Reference: deepspeed/__init__.py:268 — CLI arg group."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed-TPU json configuration")
    return parser
