"""deepspeed_tpu: a TPU-native large-scale training & inference framework.

Provides the capabilities of the DeepSpeed reference framework, re-designed for
JAX/XLA/Pallas on TPU device meshes.
"""
__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
