// Async file I/O engine for host tensor swap (NVMe offload).
//
// Reference analogue: csrc/aio/ — libaio thread-pool engine
// (py_lib/deepspeed_aio_thread.cpp, deepspeed_py_aio_handle.cpp,
// common/deepspeed_aio_common.cpp) used by runtime/swap_tensor/*.
//
// TPU-host design: a pthread worker pool draining a submission queue of
// pread/pwrite requests against preallocated files, completion tracked per
// request id.  Exposed as a plain C API for ctypes binding (no pybind11 in
// this image).  Large requests are chunked 'block_size' at a time so queue
// depth translates into real disk parallelism.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>
#include <unistd.h>

namespace {

struct Request {
  int64_t id;
  bool is_write;
  int fd;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Completion {
  int64_t remaining;   // outstanding chunks
  int64_t status;      // 0 ok, negative errno
};

class AioEngine {
 public:
  AioEngine(int num_threads, int64_t block_size)
      : block_size_(block_size), stop_(false) {
    for (int i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { this->worker(); });
    }
  }

  ~AioEngine() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t submit(bool is_write, int fd, void* buf, int64_t nbytes,
                 int64_t offset) {
    int64_t id = next_id_.fetch_add(1);
    int64_t nchunks = (nbytes + block_size_ - 1) / block_size_;
    if (nchunks == 0) nchunks = 1;
    {
      std::lock_guard<std::mutex> lk(mu_);
      completions_[id] = Completion{nchunks, 0};
      for (int64_t c = 0; c < nchunks; ++c) {
        int64_t chunk_off = c * block_size_;
        int64_t chunk_len = std::min(block_size_, nbytes - chunk_off);
        if (chunk_len <= 0) chunk_len = nbytes;  // zero-size edge
        queue_.push_back(Request{id, is_write, fd,
                                 static_cast<char*>(buf) + chunk_off, chunk_len,
                                 offset + chunk_off});
      }
    }
    cv_.notify_all();
    return id;
  }

  // Blocks until request `id` fully completes; returns 0 or -errno.
  int64_t wait(int64_t id) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this, id] {
      auto it = completions_.find(id);
      return it == completions_.end() || it->second.remaining == 0;
    });
    auto it = completions_.find(id);
    if (it == completions_.end()) return 0;
    int64_t status = it->second.status;
    completions_.erase(it);
    return status;
  }

  // Non-blocking poll: 1 done, 0 pending.
  int64_t poll(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = completions_.find(id);
    return (it == completions_.end() || it->second.remaining == 0) ? 1 : 0;
  }

 private:
  void worker() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        req = queue_.front();
        queue_.pop_front();
      }
      int64_t status = 0;
      int64_t done = 0;
      while (done < req.nbytes) {
        ssize_t n = req.is_write
            ? pwrite(req.fd, static_cast<char*>(req.buf) + done,
                     req.nbytes - done, req.offset + done)
            : pread(req.fd, static_cast<char*>(req.buf) + done,
                    req.nbytes - done, req.offset + done);
        if (n < 0) {
          status = -errno;
          break;
        }
        if (n == 0) break;  // EOF on read
        done += n;
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = completions_.find(req.id);
        if (it != completions_.end()) {
          if (status != 0 && it->second.status == 0) it->second.status = status;
          if (--it->second.remaining == 0) done_cv_.notify_all();
        }
      }
    }
  }

  int64_t block_size_;
  std::vector<std::thread> workers_;
  std::deque<Request> queue_;
  std::unordered_map<int64_t, Completion> completions_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::atomic<int64_t> next_id_{1};
  bool stop_;
};

}  // namespace

extern "C" {

void* dstpu_aio_create(int num_threads, int64_t block_size) {
  return new AioEngine(num_threads, block_size);
}

void dstpu_aio_destroy(void* handle) { delete static_cast<AioEngine*>(handle); }

int dstpu_aio_open(const char* path, int for_write) {
  int flags = for_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  return open(path, flags, 0644);
}

void dstpu_aio_close(int fd) { close(fd); }

int64_t dstpu_aio_pwrite(void* handle, int fd, void* buf, int64_t nbytes,
                         int64_t offset) {
  return static_cast<AioEngine*>(handle)->submit(true, fd, buf, nbytes, offset);
}

int64_t dstpu_aio_pread(void* handle, int fd, void* buf, int64_t nbytes,
                        int64_t offset) {
  return static_cast<AioEngine*>(handle)->submit(false, fd, buf, nbytes, offset);
}

int64_t dstpu_aio_wait(void* handle, int64_t id) {
  return static_cast<AioEngine*>(handle)->wait(id);
}

int64_t dstpu_aio_poll(void* handle, int64_t id) {
  return static_cast<AioEngine*>(handle)->poll(id);
}

}  // extern "C"
