"""Autotuning experiment scheduler with persistence (reference:
autotuning/scheduler.py ``ResourceManager`` + autotuner.py:304 experiment
dirs — each trial gets a directory with its config and recorded metrics,
so interrupted searches resume and results survive for inspection).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger
from .autotuner import Experiment


class ExperimentScheduler:
    """Runs experiments through a callable and persists per-trial results.

    ``run_fn(config_patch) -> float`` returns the metric (higher better) or
    raises.  Completed trials found on disk are skipped (resume)."""

    def __init__(self, results_dir: str = "autotuning_results",
                 cache_errors: bool = False):
        self.results_dir = results_dir
        #: False (default): failed trials RE-RUN on resume — errors here are
        #: often transient (busy TPU runtime); only successful metrics cache.
        self.cache_errors = cache_errors
        os.makedirs(results_dir, exist_ok=True)

    def _trial_dir(self, exp: Experiment) -> str:
        # keyed by name + config hash: resuming after the search space
        # changed must not return a metric recorded for a DIFFERENT
        # config_patch that happened to share the experiment name
        digest = hashlib.sha256(
            json.dumps(exp.config_patch, sort_keys=True).encode()
        ).hexdigest()[:10]
        return os.path.join(self.results_dir, f"{exp.name}-{digest}")

    def _load_cached(self, exp: Experiment) -> bool:
        path = os.path.join(self._trial_dir(exp), "metrics.json")
        if not os.path.exists(path):
            return False
        with open(path) as f:
            rec = json.load(f)
        if rec.get("metric_value") is None and not self.cache_errors:
            return False
        exp.metric_value = rec.get("metric_value")
        exp.error = rec.get("error")
        return True

    def run(self, experiments: List[Experiment],
            run_fn: Callable[[Dict[str, Any]], float]) -> List[Experiment]:
        for exp in experiments:
            if self._load_cached(exp):
                logger.info(f"autotuning: {exp.name} cached "
                            f"(metric={exp.metric_value})")
                continue
            trial = self._trial_dir(exp)
            os.makedirs(trial, exist_ok=True)
            with open(os.path.join(trial, "config.json"), "w") as f:
                json.dump(exp.config_patch, f, indent=2)
            t0 = time.perf_counter()
            try:
                exp.metric_value = float(run_fn(exp.config_patch))
            except Exception as e:  # noqa: BLE001
                exp.error = f"{type(e).__name__}: {e}"
                logger.warning(f"autotuning: {exp.name} failed: {exp.error}")
            with open(os.path.join(trial, "metrics.json"), "w") as f:
                json.dump({"metric_value": exp.metric_value,
                           "error": exp.error,
                           "wall_s": round(time.perf_counter() - t0, 3)}, f)
        self._write_summary(experiments)
        return experiments

    def _write_summary(self, experiments: List[Experiment]) -> None:
        ranked = sorted((e for e in experiments if e.metric_value is not None),
                        key=lambda e: -e.metric_value)
        summary = {
            "best": ranked[0].name if ranked else None,
            "best_metric": ranked[0].metric_value if ranked else None,
            "best_config": ranked[0].config_patch if ranked else None,
            "trials": [{"name": e.name, "metric": e.metric_value,
                        "error": e.error} for e in experiments],
        }
        with open(os.path.join(self.results_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)

    def best(self) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.results_dir, "summary.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)


def main(argv=None):
    """CLI (reference: ``deepspeed --autotuning run``): searches the config
    space for a user factory module.

        python -m deepspeed_tpu.autotuning.cli --module my_factories \\
            --results-dir autotuning_results [--max-trials N]

    The module must expose ``model_factory()``, ``params_factory()``,
    ``batch_factory(batch_size)`` and optionally ``base_config`` (dict).
    """
    import argparse
    import importlib

    parser = argparse.ArgumentParser()
    parser.add_argument("--module", required=True)
    parser.add_argument("--results-dir", default="autotuning_results")
    parser.add_argument("--max-trials", type=int, default=24)
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args(argv)

    mod = importlib.import_module(args.module)
    from .autotuner import Autotuner

    tuner = Autotuner(
        model_factory=mod.model_factory, params_factory=mod.params_factory,
        base_config=getattr(mod, "base_config", {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}),
        batch_factory=mod.batch_factory, num_steps=args.steps,
        max_trials=args.max_trials)
    exps = tuner.generate_experiments()
    sched = ExperimentScheduler(args.results_dir)
    sched.run(exps, tuner.run_experiment_patch)
    best = sched.best()
    print(json.dumps(best, indent=2))


if __name__ == "__main__":
    main()
