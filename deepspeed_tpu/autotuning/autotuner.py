"""Autotuner (reference: autotuning/autotuner.py:304 — experiment generation,
scheduler.py resource manager, tuner/{gridsearch,random,model_based}).

Searches the config space (ZeRO stage × micro-batch × remat) for the best
throughput.  The reference launches each experiment as a separate job; on TPU
a trial is just "build engine, time a few steps in-process" — compilation is
the only per-trial cost, so the whole search runs in minutes.

Model-based pruning: trials whose estimated memory exceeds the device HBM are
skipped without compiling (reference's model-info profile run, :663).
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import log_dist, logger

DEFAULT_MIN_MEM_HEADROOM = 0.9


@dataclasses.dataclass
class Experiment:
    name: str
    config_patch: Dict[str, Any]
    metric_value: Optional[float] = None   # samples/sec (higher better)
    error: Optional[str] = None


class Autotuner:
    def __init__(self, model_factory: Callable[[], Any], params_factory: Callable[[], Any],
                 base_config: Dict[str, Any], batch_factory: Callable[[int], Any],
                 topology=None, metric: str = "throughput",
                 num_steps: int = 4, warmup_steps: int = 1,
                 tuner_type: str = "gridsearch", max_trials: int = 50,
                 early_stopping: Optional[int] = None):
        self.model_factory = model_factory
        self.params_factory = params_factory
        self.base_config = base_config
        self.batch_factory = batch_factory
        self.topology = topology
        self.metric = metric
        self.num_steps = num_steps
        self.warmup_steps = warmup_steps
        self.tuner_type = tuner_type
        self.max_trials = max_trials
        self.early_stopping = early_stopping
        self.experiments: List[Experiment] = []

    # ------------------------------------------------------------------ #
    def generate_experiments(self, zero_stages: Sequence[int] = (0, 1, 2, 3),
                             micro_batches: Sequence[int] = (1, 2, 4, 8),
                             remat: Sequence[bool] = (False,)) -> List[Experiment]:
        exps = []
        for stage, mb, rm in itertools.product(zero_stages, micro_batches, remat):
            patch = {"zero_optimization": {"stage": stage},
                     "train_micro_batch_size_per_gpu": mb}
            exps.append(Experiment(name=f"z{stage}_mb{mb}_remat{int(rm)}",
                                   config_patch=patch))
        if self.tuner_type == "random":
            rng = np.random.default_rng(0)
            rng.shuffle(exps)
        return exps[:self.max_trials]

    def estimated_memory(self, patch: Dict[str, Any], param_bytes: int,
                         dp_size: int) -> int:
        """Rough model-based memory estimate (params + grads + adam moments),
        scaled by the ZeRO stage's partitioning."""
        stage = patch.get("zero_optimization", {}).get("stage", 0)
        p = param_bytes
        grads = p
        opt = 2 * p + p  # m, v, fp32 master
        if stage >= 1:
            opt //= dp_size
        if stage >= 2:
            grads //= dp_size
        if stage >= 3:
            p //= dp_size
        return p + grads + opt

    # ------------------------------------------------------------------ #
    def run_experiment(self, exp: Experiment) -> Experiment:
        import deepspeed_tpu

        config = _deep_merge(dict(self.base_config), exp.config_patch)
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model_factory(), model_parameters=self.params_factory(),
                config=config, topology=self.topology)
            batch = self.batch_factory(engine.train_batch_size())
            for _ in range(self.warmup_steps):
                loss = engine.train_batch(batch)
            import jax

            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(self.num_steps):
                loss = engine.train_batch(batch)
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            exp.metric_value = engine.train_batch_size() * self.num_steps / dt
        except Exception as e:  # OOM / invalid config → record, keep tuning
            exp.error = f"{type(e).__name__}: {e}"
            logger.warning(f"experiment {exp.name} failed: {exp.error[:120]}")
        return exp

    def run_experiment_patch(self, config_patch: Dict[str, Any]) -> float:
        """Scheduler-facing single-trial entry: run one config patch and
        return its metric (raises on failure so the scheduler records it)."""
        exp = Experiment(name="trial", config_patch=config_patch)
        self.run_experiment(exp)
        if exp.error is not None:
            raise RuntimeError(exp.error)
        return exp.metric_value

    def tune(self, **gen_kwargs) -> Optional[Experiment]:
        exps = self.generate_experiments(**gen_kwargs)
        best: Optional[Experiment] = None
        stale = 0
        for exp in exps:
            self.run_experiment(exp)
            self.experiments.append(exp)
            if exp.metric_value is not None and \
                    (best is None or exp.metric_value > best.metric_value):
                best = exp
                stale = 0
            else:
                stale += 1
            log_dist(f"autotuner: {exp.name} -> "
                     f"{exp.metric_value and round(exp.metric_value, 2)} samples/s",
                     ranks=[0])
            if self.early_stopping and stale >= self.early_stopping:
                break
        if best:
            log_dist(f"autotuner best: {best.name} "
                     f"({best.metric_value:.2f} samples/s)", ranks=[0])
        return best

    def best_config(self) -> Optional[Dict[str, Any]]:
        done = [e for e in self.experiments if e.metric_value is not None]
        if not done:
            return None
        best = max(done, key=lambda e: e.metric_value)
        return _deep_merge(dict(self.base_config), best.config_patch)


def _deep_merge(base: Dict, patch: Dict) -> Dict:
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
