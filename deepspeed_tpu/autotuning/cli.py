"""Autotuning CLI entry (reference: ``deepspeed --autotuning run``):
``python -m deepspeed_tpu.autotuning.cli --module my_factories``."""
from .scheduler import main

if __name__ == "__main__":
    main()
