"""MoE module wrapper (reference: deepspeed/moe/layer.py:17 ``MoE``).

Bundles gate + experts with DeepSpeed's constructor signature; functional
like every layer in this framework: ``init_params`` returns the pytree,
``__call__`` applies it.  ``partition_specs`` shards experts over the
"expert" mesh axis (EP); data-parallel replication of the gate and
expert-data-parallel gradient reduction fall out of the mesh shardings
(reference handles this with dedicated process groups,
utils/groups.py:236,376, and `_reduce_expert_gradients`, engine.py:2588).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..runtime.topology import get_topology
from .sharded_moe import init_moe_params, moe_layer, moe_partition_specs


class MoE:
    def __init__(self, hidden_size: int, expert=None, num_experts: int = 1,
                 ep_size: int = 1, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 use_residual: bool = False, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 ffn_hidden_size: Optional[int] = None, activation=jax.nn.gelu):
        if num_experts % max(ep_size, 1) != 0:
            raise ValueError(f"num_experts({num_experts}) must divide by ep_size({ep_size})")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.use_residual = use_residual
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.activation = activation
        self.partition_specs = moe_partition_specs()
        if use_residual:
            from jax.sharding import PartitionSpec as P

            self.partition_specs = {
                "moe": self.partition_specs,
                "residual_mlp": {"w1": P(None, None), "b1": P(None),
                                 "w2": P(None, None), "b2": P(None)},
                "coefficient": {"kernel": P(None, None)},
            }

    def init_params(self, key: jax.Array, dtype=jnp.float32) -> Dict:
        moe_p = init_moe_params(key, self.hidden_size, self.ffn_hidden_size,
                                self.num_experts, dtype)
        if not self.use_residual:
            return moe_p
        import math

        k1, k2, k3 = jax.random.split(key, 3)
        s1 = 1.0 / math.sqrt(self.hidden_size)
        return {
            "moe": moe_p,
            "residual_mlp": {
                "w1": (jax.random.normal(k1, (self.hidden_size, self.ffn_hidden_size)) * s1).astype(dtype),
                "b1": jnp.zeros((self.ffn_hidden_size,), dtype),
                "w2": (jax.random.normal(k2, (self.ffn_hidden_size, self.hidden_size)) *
                       (1.0 / math.sqrt(self.ffn_hidden_size))).astype(dtype),
                "b2": jnp.zeros((self.hidden_size,), dtype),
            },
            "coefficient": {"kernel": (jax.random.normal(k3, (self.hidden_size, 2)) * s1).astype(dtype)},
        }

    def __call__(self, params: Dict, hidden_states: jnp.ndarray,
                 rng: Optional[jax.Array] = None, training: bool = True):
        """Returns (output, l_aux, exp_counts) like the reference MoE.forward."""
        moe_p = params["moe"] if self.use_residual else params
        out, l_aux, counts = moe_layer(
            moe_p, hidden_states, k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity, drop_tokens=self.drop_tokens,
            noisy_gate_policy=self.noisy_gate_policy, rng=rng,
            training=training, activation=self.activation)
        if self.use_residual:
            # MoS residual (reference layer.py residual_mlp + coefficient mix)
            h = self.activation(hidden_states @ params["residual_mlp"]["w1"] +
                                params["residual_mlp"]["b1"])
            res = h @ params["residual_mlp"]["w2"] + params["residual_mlp"]["b2"]
            coef = jax.nn.softmax(hidden_states @ params["coefficient"]["kernel"], axis=-1)
            out = out * coef[..., 0:1] + res * coef[..., 1:2]
        return out, l_aux, counts
