"""Mixture-of-Experts layer with expert parallelism.

Reference analogue: ``deepspeed/moe/sharded_moe.py`` — top1/top2/topk gating
(:183,:290,:374), ``MOELayer`` einsum dispatch → all-to-all → experts →
all-to-all → combine (:533,:586), capacity/drop logic, load-balance aux loss.

TPU-native formulation (GShard-style): gating produces dense one-hot
dispatch/combine tensors [S, E, C]; the dispatch/collect are einsums over
stacked expert weights [E, ...] sharded on the "expert" mesh axis, so XLA
lowers the token exchange to an all-to-all over ICI — no hand-written NCCL
all_to_all_single as in the reference (:96 _AllToAll).  Shapes are static
(capacity padding), which keeps everything jit-compatible.
"""
from __future__ import annotations

import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import EXPERT, get_topology


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray          # load-balance loss
    combine: jnp.ndarray        # [S, E, C] float combine weights
    dispatch: jnp.ndarray       # [S, E, C] bool dispatch mask
    exp_counts: jnp.ndarray     # [E] tokens routed per expert (pre-drop)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top1gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
               min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None, drop_tokens: bool = True,
               used_capacity: Any = None) -> GateOutput:
    """Switch-style top-1 gating (reference: sharded_moe.py:183)."""
    S, E = logits.shape
    C = _capacity(S, E, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    select_logits = logits
    if noisy_gate_policy == "RSample" and rng is not None:
        select_logits = logits + jax.random.gumbel(rng, logits.shape)
    idx = jnp.argmax(select_logits, axis=1)                       # [S]
    mask = _one_hot(idx, E)                                       # [S, E]

    # Load-balance loss (Switch):  E * Σ_e mean_tokens(mask_e) * mean(gates_e)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask, axis=0)
    l_aux = jnp.sum(me * ce) * E

    pos = jnp.cumsum(mask, axis=0) - mask                         # position in expert
    if drop_tokens:
        mask = mask * (pos < C)
    pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)  # [S]
    gate_val = jnp.sum(gates * mask, axis=1)                      # [S]

    dispatch = (mask[:, :, None] *
                _one_hot(pos_in_expert, C)[:, None, :])           # [S, E, C]
    combine = dispatch * gate_val[:, None, None]
    return GateOutput(l_aux, combine, dispatch.astype(bool),
                      jnp.sum(_one_hot(idx, E), axis=0).astype(jnp.int32))


def topkgating(logits: jnp.ndarray, k: int = 2, capacity_factor: float = 1.0,
               min_capacity: int = 4, drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               normalize_weights: bool = True) -> GateOutput:
    """Top-k gating (reference: sharded_moe.py:374; k=2 ≡ top2gating :290)."""
    S, E = logits.shape
    C = _capacity(S * k, E, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    topk_val, topk_idx = jax.lax.top_k(gates, k)                  # [S, k]
    if normalize_weights:
        topk_val = topk_val / jnp.sum(topk_val, axis=1, keepdims=True)

    # masks per choice, cumulative positions account for earlier choices
    combine = jnp.zeros((S, E, C), jnp.float32)
    dispatch = jnp.zeros((S, E, C), jnp.bool_)
    counts = jnp.zeros((E,), jnp.float32)                          # running per-expert fill
    ce_total = jnp.zeros((E,), jnp.float32)
    for choice in range(k):
        idx = topk_idx[:, choice]
        mask = _one_hot(idx, E)                                   # [S, E]
        ce_total = ce_total + jnp.sum(mask, axis=0)
        pos = jnp.cumsum(mask, axis=0) - mask + counts[None, :]
        if drop_tokens:
            mask = mask * (pos < C)
        counts = counts + jnp.sum(mask, axis=0)
        pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)
        d = mask[:, :, None] * _one_hot(pos_in_expert, C)[:, None, :]
        dispatch = jnp.logical_or(dispatch, d.astype(bool))
        combine = combine + d * topk_val[:, choice][:, None, None]

    me = jnp.mean(gates, axis=0)
    ce = ce_total / jnp.maximum(jnp.sum(ce_total), 1.0)
    l_aux = jnp.sum(me * ce) * E
    return GateOutput(l_aux, combine, dispatch, ce_total.astype(jnp.int32))


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GateOutput:
    return topkgating(logits, k=2, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


# --------------------------------------------------------------------- #
# Expert FFN + MOELayer
# --------------------------------------------------------------------- #
def init_moe_params(key, hidden: int, ffn: int, num_experts: int,
                    dtype=jnp.float32) -> Dict:
    """Gate + stacked expert FFN params (reference Experts: moe/experts.py:13)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale1 = 1.0 / math.sqrt(hidden)
    scale2 = 1.0 / math.sqrt(ffn)
    return {
        "gate": {"kernel": (jax.random.normal(k1, (hidden, num_experts)) * scale1
                            ).astype(jnp.float32)},  # gate stays fp32 (reference keeps it)
        "experts": {
            "w1": (jax.random.normal(k2, (num_experts, hidden, ffn)) * scale1).astype(dtype),
            "b1": jnp.zeros((num_experts, ffn), dtype),
            "w2": (jax.random.normal(k3, (num_experts, ffn, hidden)) * scale2).astype(dtype),
            "b2": jnp.zeros((num_experts, hidden), dtype),
        },
    }


def moe_partition_specs() -> Dict:
    """Expert weights sharded over the "expert" mesh axis; gate replicated."""
    return {
        "gate": {"kernel": P(None, None)},
        "experts": {
            "w1": P(EXPERT, None, None),
            "b1": P(EXPERT, None),
            "w2": P(EXPERT, None, None),
            "b2": P(EXPERT, None),
        },
    }


def dispatch_to_experts(dispatch: jnp.ndarray, tokens: jnp.ndarray,
                        dtype) -> jnp.ndarray:
    """[S,E,C] mask × [S,D] tokens → [E,C,D] expert inputs (the GShard
    dispatch einsum; shared by moe_layer and the MoE transformer block)."""
    return jnp.einsum("sec,sd->ecd", dispatch.astype(dtype), tokens.astype(dtype))


def combine_from_experts(combine: jnp.ndarray, expert_out: jnp.ndarray,
                         dtype) -> jnp.ndarray:
    """[S,E,C] weights × [E,C,D] expert outputs → [S,D]."""
    return jnp.einsum("sec,ecd->sd", combine.astype(dtype), expert_out)


def moe_layer(params: Dict, x: jnp.ndarray, k: int = 1,
              capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
              min_capacity: int = 4, drop_tokens: bool = True,
              noisy_gate_policy: Optional[str] = None,
              rng: Optional[jax.Array] = None, training: bool = True,
              activation=jax.nn.gelu) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Apply the MoE layer to x [..., D] → (out [..., D], l_aux, exp_counts).

    Reference: MOELayer.forward (sharded_moe.py:586): einsum dispatch →
    all-to-all → expert FFN → all-to-all → einsum combine.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    tokens = x.reshape(-1, D)
    S = tokens.shape[0]
    logits = tokens.astype(jnp.float32) @ params["gate"]["kernel"]
    cf = capacity_factor if training else eval_capacity_factor
    if k == 1:
        gate = top1gating(logits, cf, min_capacity, noisy_gate_policy, rng, drop_tokens)
    else:
        gate = topkgating(logits, k, cf, min_capacity, drop_tokens, rng)

    w = params["experts"]
    dtype = w["w1"].dtype
    dispatched = dispatch_to_experts(gate.dispatch, tokens, dtype)  # [E, C, D]
    h = activation(jnp.einsum("ecd,edf->ecf", dispatched, w["w1"]) + w["b1"][:, None, :])
    expert_out = jnp.einsum("ecf,efd->ecd", h, w["w2"]) + w["b2"][:, None, :]
    out = combine_from_experts(gate.combine, expert_out, dtype)
    return out.reshape(orig_shape), gate.l_aux, gate.exp_counts
