"""Mixture-of-Experts layer with expert parallelism.

Reference analogue: ``deepspeed/moe/sharded_moe.py`` — top1/top2/topk gating
(:183,:290,:374), ``MOELayer`` einsum dispatch → all-to-all → experts →
all-to-all → combine (:533,:586), capacity/drop logic, load-balance aux loss.

TPU-native formulation (GShard-style): gating produces dense one-hot
dispatch/combine tensors [S, E, C]; the dispatch/collect are einsums over
stacked expert weights [E, ...] sharded on the "expert" mesh axis, so XLA
lowers the token exchange to an all-to-all over ICI — no hand-written NCCL
all_to_all_single as in the reference (:96 _AllToAll).  Shapes are static
(capacity padding), which keeps everything jit-compatible.
"""
from __future__ import annotations

import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import EXPERT, get_topology


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray          # load-balance loss
    combine: jnp.ndarray        # [S, E, C] float combine weights
    dispatch: jnp.ndarray       # [S, E, C] bool dispatch mask
    exp_counts: jnp.ndarray     # [E] tokens routed per expert (pre-drop)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _mask_padded_experts(logits: jnp.ndarray,
                         num_experts_logical: Optional[int]) -> Tuple[jnp.ndarray, int]:
    """Routing over a padded expert stack (elastic resharding onto an
    ``ep_size`` that does not divide the expert count pads the stack to the
    next multiple — see :func:`pad_experts_for_ep`): padding columns get
    ``-inf`` logits, so softmax/argmax/top-k are bit-identical to the
    unpadded layer (``exp(-inf) == 0`` leaves every denominator unchanged).
    Returns (masked logits, logical expert count) — capacity and the
    load-balance loss must use the LOGICAL count, or padding would shrink
    per-expert capacity and change routing decisions."""
    E = logits.shape[1]
    if num_experts_logical is None or num_experts_logical >= E:
        return logits, E
    mask = jnp.where(jnp.arange(E) < num_experts_logical, 0.0, -jnp.inf)
    return logits + mask[None, :], int(num_experts_logical)


def top1gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
               min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None, drop_tokens: bool = True,
               used_capacity: Any = None,
               num_experts_logical: Optional[int] = None) -> GateOutput:
    """Switch-style top-1 gating (reference: sharded_moe.py:183)."""
    S, E = logits.shape
    logits, n_log = _mask_padded_experts(logits, num_experts_logical)
    C = _capacity(S, n_log, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    select_logits = logits
    if noisy_gate_policy == "RSample" and rng is not None:
        select_logits = logits + jax.random.gumbel(rng, logits.shape)
    idx = jnp.argmax(select_logits, axis=1)                       # [S]
    mask = _one_hot(idx, E)                                       # [S, E]

    # Load-balance loss (Switch):  E * Σ_e mean_tokens(mask_e) * mean(gates_e)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask, axis=0)
    l_aux = jnp.sum(me * ce) * n_log

    pos = jnp.cumsum(mask, axis=0) - mask                         # position in expert
    if drop_tokens:
        mask = mask * (pos < C)
    pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)  # [S]
    gate_val = jnp.sum(gates * mask, axis=1)                      # [S]

    dispatch = (mask[:, :, None] *
                _one_hot(pos_in_expert, C)[:, None, :])           # [S, E, C]
    combine = dispatch * gate_val[:, None, None]
    return GateOutput(l_aux, combine, dispatch.astype(bool),
                      jnp.sum(_one_hot(idx, E), axis=0).astype(jnp.int32))


def topkgating(logits: jnp.ndarray, k: int = 2, capacity_factor: float = 1.0,
               min_capacity: int = 4, drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               normalize_weights: bool = True,
               num_experts_logical: Optional[int] = None) -> GateOutput:
    """Top-k gating (reference: sharded_moe.py:374; k=2 ≡ top2gating :290)."""
    S, E = logits.shape
    logits, n_log = _mask_padded_experts(logits, num_experts_logical)
    C = _capacity(S * k, n_log, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    topk_val, topk_idx = jax.lax.top_k(gates, k)                  # [S, k]
    if normalize_weights:
        topk_val = topk_val / jnp.sum(topk_val, axis=1, keepdims=True)

    # masks per choice, cumulative positions account for earlier choices
    combine = jnp.zeros((S, E, C), jnp.float32)
    dispatch = jnp.zeros((S, E, C), jnp.bool_)
    counts = jnp.zeros((E,), jnp.float32)                          # running per-expert fill
    ce_total = jnp.zeros((E,), jnp.float32)
    for choice in range(k):
        idx = topk_idx[:, choice]
        mask = _one_hot(idx, E)                                   # [S, E]
        ce_total = ce_total + jnp.sum(mask, axis=0)
        pos = jnp.cumsum(mask, axis=0) - mask + counts[None, :]
        if drop_tokens:
            mask = mask * (pos < C)
        counts = counts + jnp.sum(mask, axis=0)
        pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)
        d = mask[:, :, None] * _one_hot(pos_in_expert, C)[:, None, :]
        dispatch = jnp.logical_or(dispatch, d.astype(bool))
        combine = combine + d * topk_val[:, choice][:, None, None]

    me = jnp.mean(gates, axis=0)
    ce = ce_total / jnp.maximum(jnp.sum(ce_total), 1.0)
    l_aux = jnp.sum(me * ce) * n_log
    return GateOutput(l_aux, combine, dispatch, ce_total.astype(jnp.int32))


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GateOutput:
    return topkgating(logits, k=2, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


# --------------------------------------------------------------------- #
# Sparse (scatter/gather) dispatch — the scalable path
# --------------------------------------------------------------------- #
class SparseGateOutput(NamedTuple):
    """Routing as flat slot ids instead of dense [S,E,C] one-hots.

    ``slot[s, choice]`` = expert*C + position-in-expert, or E*C (a trash row)
    when the token was dropped; ``gate_val`` carries the combine weight
    (zeroed for drops).  Dispatch becomes an O(S·D) scatter-add and combine
    an O(S·D) gather — vs the dense einsum's O(S·E·C·D) ≈ O(S²·k·D), which
    is quadratic in routing-chunk tokens (reference sharded_moe.py:533's
    einsum dispatch has the same blowup; its sort-based top-k path :374 is
    the analogue of this).
    """
    l_aux: jnp.ndarray
    slot: jnp.ndarray           # [S, k] int32
    gate_val: jnp.ndarray       # [S, k] f32
    exp_counts: jnp.ndarray     # [E]
    capacity: int


def top1gating_sparse(logits: jnp.ndarray, capacity_factor: float = 1.0,
                      min_capacity: int = 4,
                      noisy_gate_policy: Optional[str] = None,
                      rng: Optional[jax.Array] = None,
                      drop_tokens: bool = True,
                      num_experts_logical: Optional[int] = None) -> SparseGateOutput:
    """Sparse-form top-1 gating; routing decisions identical to top1gating."""
    S, E = logits.shape
    logits, n_log = _mask_padded_experts(logits, num_experts_logical)
    C = _capacity(S, n_log, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    select_logits = logits
    if noisy_gate_policy == "RSample" and rng is not None:
        select_logits = logits + jax.random.gumbel(rng, logits.shape)
    idx = jnp.argmax(select_logits, axis=1)
    mask = _one_hot(idx, E)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask, axis=0)
    l_aux = jnp.sum(me * ce) * n_log

    pos = jnp.cumsum(mask, axis=0) - mask
    if drop_tokens:
        mask = mask * (pos < C)
    kept = jnp.sum(mask, axis=1) > 0
    pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)
    gate_val = jnp.sum(gates * mask, axis=1)
    # Beyond-capacity tokens always go to the trash row: the dense path's
    # one_hot(pos>=C, C) row is all-zeros, i.e. a silent zero-contribution —
    # a raw idx*C+pos slot would land in the NEXT expert's rows.
    kept = jnp.logical_and(kept, pos_in_expert < C)
    slot = jnp.where(kept, idx.astype(jnp.int32) * C + pos_in_expert, E * C)
    counts = jnp.sum(_one_hot(idx, E), axis=0).astype(jnp.int32)
    return SparseGateOutput(l_aux, slot[:, None], gate_val[:, None], counts, C)


def topkgating_sparse(logits: jnp.ndarray, k: int = 2,
                      capacity_factor: float = 1.0, min_capacity: int = 4,
                      drop_tokens: bool = True,
                      rng: Optional[jax.Array] = None,
                      normalize_weights: bool = True,
                      valid: Optional[jnp.ndarray] = None,
                      num_experts_logical: Optional[int] = None) -> SparseGateOutput:
    """Sparse-form top-k gating; routing decisions identical to topkgating.

    ``valid`` [S] bool: tokens marked invalid (ragged-batch padding) are
    routed to the trash slot and consume no expert capacity.
    """
    S, E = logits.shape
    logits, n_log = _mask_padded_experts(logits, num_experts_logical)
    C = _capacity(S * k, n_log, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=1)

    topk_val, topk_idx = jax.lax.top_k(gates, k)
    if normalize_weights:
        topk_val = topk_val / jnp.sum(topk_val, axis=1, keepdims=True)

    slots, vals = [], []
    counts = jnp.zeros((E,), jnp.float32)
    ce_total = jnp.zeros((E,), jnp.float32)
    for choice in range(k):
        idx = topk_idx[:, choice]
        mask = _one_hot(idx, E)
        if valid is not None:
            mask = mask * valid.astype(jnp.float32)[:, None]
        ce_total = ce_total + jnp.sum(mask, axis=0)
        pos = jnp.cumsum(mask, axis=0) - mask + counts[None, :]
        if drop_tokens:
            mask = mask * (pos < C)
        counts = counts + jnp.sum(mask, axis=0)
        kept = jnp.sum(mask, axis=1) > 0
        pos_in_expert = jnp.sum(pos * mask, axis=1).astype(jnp.int32)
        # beyond-capacity → trash row (dense one_hot(pos>=C) is all-zeros)
        kept = jnp.logical_and(kept, pos_in_expert < C)
        slots.append(jnp.where(kept, idx.astype(jnp.int32) * C + pos_in_expert,
                               E * C))
        vals.append(jnp.where(kept, topk_val[:, choice], 0.0))

    me = jnp.mean(gates, axis=0)
    ce = ce_total / jnp.maximum(jnp.sum(ce_total), 1.0)
    l_aux = jnp.sum(me * ce) * n_log
    return SparseGateOutput(l_aux, jnp.stack(slots, axis=1),
                            jnp.stack(vals, axis=1),
                            ce_total.astype(jnp.int32), C)


def dispatch_sparse(slot: jnp.ndarray, tokens: jnp.ndarray, num_experts: int,
                    capacity: int, dtype) -> jnp.ndarray:
    """[S,k] slots × [S,D] tokens → [E,C,D] via scatter-add (O(S·k·D))."""
    S, D = tokens.shape
    EC = num_experts * capacity
    flat = jnp.zeros((EC + 1, D), dtype)          # +1 trash row for drops
    t = tokens.astype(dtype)
    for choice in range(slot.shape[1]):
        flat = flat.at[slot[:, choice]].add(t)
    return flat[:EC].reshape(num_experts, capacity, D)


def _pin_replicated(x: jnp.ndarray) -> jnp.ndarray:
    """Constrain ``x`` fully replicated on the global mesh (no-op on a
    trivial mesh or inside a manual shard_map region).

    Guards the sparse combine's gather against a GSPMD miscompile: with
    ``expert_out`` sharded on the expert axis and slots/tokens carrying a
    batch sharding, GSPMD partitions ``jnp.take`` into per-shard gathers
    and sums the partial contributions over EVERY replica group — including
    the pure data-replica groups — so the combined output comes back
    multiplied by the data-axis size (observed exactly 4x on an 8-device
    data4×expert2 mesh; same bug class PR 8 fixed in ``paged_kv_append``'s
    row-scatter).  Replicating the gather operand first makes the gather
    local and keeps the cross-expert exchange as one explicit all-gather.
    """
    from ..runtime import topology as _topo

    topo = _topo._TOPOLOGY
    if topo is None or topo.mesh.size <= 1:
        return x
    _, manual = _topo.shard_map_context(topo)
    if manual:
        # inside a partial-manual region constraint specs may not name
        # manual axes; the manual body already owns its collectives
        return x
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(topo.mesh, P()))


def combine_sparse(slot: jnp.ndarray, gate_val: jnp.ndarray,
                   expert_out: jnp.ndarray, dtype) -> jnp.ndarray:
    """[S,k] slots + weights × [E,C,D] expert outputs → [S,D] via gather."""
    E, C, D = expert_out.shape
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, D),
         jnp.zeros((1, D), expert_out.dtype)], axis=0)
    flat = _pin_replicated(flat)
    out = None
    for choice in range(slot.shape[1]):
        contrib = gate_val[:, choice, None].astype(dtype) * \
            jnp.take(flat, slot[:, choice], axis=0).astype(dtype)
        out = contrib if out is None else out + contrib
    return out


# --------------------------------------------------------------------- #
# Expert FFN + MOELayer
# --------------------------------------------------------------------- #
def init_moe_params(key, hidden: int, ffn: int, num_experts: int,
                    dtype=jnp.float32) -> Dict:
    """Gate + stacked expert FFN params (reference Experts: moe/experts.py:13)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale1 = 1.0 / math.sqrt(hidden)
    scale2 = 1.0 / math.sqrt(ffn)
    return {
        "gate": {"kernel": (jax.random.normal(k1, (hidden, num_experts)) * scale1
                            ).astype(jnp.float32)},  # gate stays fp32 (reference keeps it)
        "experts": {
            "w1": (jax.random.normal(k2, (num_experts, hidden, ffn)) * scale1).astype(dtype),
            "b1": jnp.zeros((num_experts, ffn), dtype),
            "w2": (jax.random.normal(k3, (num_experts, ffn, hidden)) * scale2).astype(dtype),
            "b2": jnp.zeros((num_experts, hidden), dtype),
        },
    }


def moe_partition_specs() -> Dict:
    """Expert weights sharded over the "expert" mesh axis; gate replicated."""
    return {
        "gate": {"kernel": P(None, None)},
        "experts": {
            "w1": P(EXPERT, None, None),
            "b1": P(EXPERT, None),
            "w2": P(EXPERT, None, None),
            "b2": P(EXPERT, None),
        },
    }


def dispatch_to_experts(dispatch: jnp.ndarray, tokens: jnp.ndarray,
                        dtype) -> jnp.ndarray:
    """[S,E,C] mask × [S,D] tokens → [E,C,D] expert inputs (the GShard
    dispatch einsum; shared by moe_layer and the MoE transformer block)."""
    return jnp.einsum("sec,sd->ecd", dispatch.astype(dtype), tokens.astype(dtype))


def combine_from_experts(combine: jnp.ndarray, expert_out: jnp.ndarray,
                         dtype) -> jnp.ndarray:
    """[S,E,C] weights × [E,C,D] expert outputs → [S,D]."""
    return jnp.einsum("sec,ecd->sd", combine.astype(dtype), expert_out)


def moe_mlp_block(lp: Dict, tokens: jnp.ndarray, k: int = 2,
                  capacity_factor: float = 2.0, dispatch_impl: str = "sparse",
                  rng: Optional[jax.Array] = None,
                  valid: Optional[jnp.ndarray] = None,
                  num_experts_logical: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mixtral-style routed SwiGLU expert MLP over flat tokens [T, D].

    ``lp`` carries router [D,E] (f32) + stacked expert weights
    gate_proj/up_proj [E,D,F], down_proj [E,F,D].  Shared by the training
    transformer (models/transformer.py) and the ragged serving runner, so
    train and serve route identically.  Router always runs in f32 (the
    reference keeps the gate fp32; under bf16 compute we re-cast to preserve
    routing decisions).
    """
    assert dispatch_impl in ("sparse", "dense"), dispatch_impl
    logits_r = tokens.astype(jnp.float32) @ lp["router"]["kernel"].astype(jnp.float32)
    dtype = lp["gate_proj"]["kernel"].dtype
    if dispatch_impl == "sparse":
        gate_out = topkgating_sparse(logits_r, k=k,
                                     capacity_factor=capacity_factor, rng=rng,
                                     valid=valid,
                                     num_experts_logical=num_experts_logical)
        dispatched = dispatch_sparse(gate_out.slot, tokens,
                                     logits_r.shape[1], gate_out.capacity, dtype)
    else:
        assert valid is None, "ragged validity masks need dispatch_impl='sparse'"
        gate_out = topkgating(logits_r, k=k, capacity_factor=capacity_factor,
                              rng=rng,
                              num_experts_logical=num_experts_logical)
        dispatched = dispatch_to_experts(gate_out.dispatch, tokens, dtype)
    act = jax.nn.silu(jnp.einsum("ecd,edf->ecf", dispatched,
                                 lp["gate_proj"]["kernel"]))
    up = jnp.einsum("ecd,edf->ecf", dispatched, lp["up_proj"]["kernel"])
    eo = jnp.einsum("ecf,efd->ecd", act * up, lp["down_proj"]["kernel"])
    if dispatch_impl == "sparse":
        out = combine_sparse(gate_out.slot, gate_out.gate_val, eo, dtype)
    else:
        out = combine_from_experts(gate_out.combine, eo, dtype)
    return out, gate_out.l_aux


def moe_layer(params: Dict, x: jnp.ndarray, k: int = 1,
              capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
              min_capacity: int = 4, drop_tokens: bool = True,
              noisy_gate_policy: Optional[str] = None,
              rng: Optional[jax.Array] = None, training: bool = True,
              activation=jax.nn.gelu,
              dispatch_impl: str = "sparse",
              num_experts_logical: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Apply the MoE layer to x [..., D] → (out [..., D], l_aux, exp_counts).

    Reference: MOELayer.forward (sharded_moe.py:586): einsum dispatch →
    all-to-all → expert FFN → all-to-all → einsum combine.

    ``dispatch_impl``: "sparse" (default) routes via flat-slot scatter/gather
    — linear in tokens, required for 32k+ routing chunks; "dense" is the
    GShard [S,E,C] einsum kept as the numerics oracle.
    """
    assert dispatch_impl in ("sparse", "dense"), dispatch_impl
    orig_shape = x.shape
    D = orig_shape[-1]
    tokens = x.reshape(-1, D)
    S = tokens.shape[0]
    logits = tokens.astype(jnp.float32) @ params["gate"]["kernel"]
    cf = capacity_factor if training else eval_capacity_factor

    w = params["experts"]
    dtype = w["w1"].dtype

    def expert_ffn(dispatched):
        h = activation(jnp.einsum("ecd,edf->ecf", dispatched, w["w1"]) +
                       w["b1"][:, None, :])
        return jnp.einsum("ecf,efd->ecd", h, w["w2"]) + w["b2"][:, None, :]

    if dispatch_impl == "sparse":
        if k == 1:
            gate = top1gating_sparse(logits, cf, min_capacity,
                                     noisy_gate_policy, rng, drop_tokens,
                                     num_experts_logical=num_experts_logical)
        else:
            gate = topkgating_sparse(logits, k, cf, min_capacity, drop_tokens,
                                     rng,
                                     num_experts_logical=num_experts_logical)
        E = logits.shape[1]
        dispatched = dispatch_sparse(gate.slot, tokens, E, gate.capacity, dtype)
        expert_out = expert_ffn(dispatched)
        out = combine_sparse(gate.slot, gate.gate_val, expert_out, dtype)
    else:
        if k == 1:
            gate = top1gating(logits, cf, min_capacity, noisy_gate_policy, rng,
                              drop_tokens,
                              num_experts_logical=num_experts_logical)
        else:
            gate = topkgating(logits, k, cf, min_capacity, drop_tokens, rng,
                              num_experts_logical=num_experts_logical)
        dispatched = dispatch_to_experts(gate.dispatch, tokens, dtype)  # [E, C, D]
        expert_out = expert_ffn(dispatched)
        out = combine_from_experts(gate.combine, expert_out, dtype)
    return out.reshape(orig_shape), gate.l_aux, gate.exp_counts


# --------------------------------------------------------------------- #
# Expert resharding (elastic mesh-shape change, universal checkpoints)
# --------------------------------------------------------------------- #
def expert_shard_ranges(num_experts: int, ep_size: int) -> list:
    """Contiguous logical-expert ranges ``[(start, stop), ...]`` per
    expert-parallel rank, balanced for uneven remainders (sizes differ by
    at most one; the first ``num_experts % ep_size`` ranks carry the extra
    expert).  This is the IDEAL balanced split — what a reader that can
    address arbitrary rows should fetch per rank."""
    E, ep = int(num_experts), max(int(ep_size), 1)
    base, rem = divmod(E, ep)
    out, start = [], 0
    for r in range(ep):
        n = base + (1 if r < rem else 0)
        out.append((start, start + n))
        start += n
    return out


def placed_expert_ranges(num_experts: int, ep_size: int) -> list:
    """The LOGICAL expert rows each rank actually holds after
    :func:`pad_experts_for_ep` + even NamedSharding chunking of the padded
    stack: rank ``r`` owns padded rows ``[r*chunk, (r+1)*chunk)`` clipped
    to the logical count (trailing ranks may hold only padding → empty
    range).  Divisible counts make this identical to
    :func:`expert_shard_ranges`."""
    E, ep = int(num_experts), max(int(ep_size), 1)
    chunk = padded_expert_count(E, ep) // ep
    return [(min(r * chunk, E), min((r + 1) * chunk, E)) for r in range(ep)]


def padded_expert_count(num_experts: int, ep_size: int) -> int:
    """Smallest multiple of ``ep_size`` holding ``num_experts`` — the
    stacked-expert leading dim after :func:`pad_experts_for_ep` (jax
    NamedSharding requires even divisibility, the GSPMD pad trick)."""
    ep = max(int(ep_size), 1)
    return -(-int(num_experts) // ep) * ep


def _pad_axis(arr: jnp.ndarray, axis: int, target: int) -> jnp.ndarray:
    pad = target - arr.shape[axis]
    if pad <= 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return jnp.pad(arr, widths)


def pad_experts_for_ep(params: Dict, ep_size: int) -> Tuple[Dict, int]:
    """Pad a stacked-expert param tree so the expert axis divides
    ``ep_size`` — expert stacks get zero experts appended (axis 0) and the
    gate/router kernel gets matching zero columns (axis 1).

    Returns ``(padded params, num_experts_logical)``.  Callers MUST pass
    the logical count to the gating functions (``num_experts_logical=``):
    padded experts route ``-inf`` logits, so outputs are bit-identical to
    the unpadded layer while the weights shard evenly.  Supports both
    param families: ``gate``+``experts`` (:func:`moe_layer`) and
    ``router``+``*_proj`` (:func:`moe_mlp_block`).
    """
    gate_key = "gate" if "gate" in params else "router"
    if gate_key not in params:
        raise ValueError("not a MoE param tree: no 'gate' or 'router' entry")
    E = int(params[gate_key]["kernel"].shape[1])
    E_pad = padded_expert_count(E, ep_size)
    if E_pad == E:
        return params, E
    out = dict(params)
    out[gate_key] = {"kernel": _pad_axis(params[gate_key]["kernel"], 1, E_pad)}
    if "experts" in params:
        out["experts"] = {k: _pad_axis(v, 0, E_pad)
                          for k, v in params["experts"].items()}
    for k in ("gate_proj", "up_proj", "down_proj"):
        if k in params:
            out[k] = {"kernel": _pad_axis(params[k]["kernel"], 0, E_pad)}
    return out, E


def reshard_expert_params(params: Dict, topology=None) -> Tuple[Dict, Dict]:
    """Lay a stacked-expert MoE param tree out for the CURRENT mesh's
    expert axis — the MoE leg of a mesh-shape change (chips lost, ep_size
    re-planned, train→serve).

    When the logical expert count divides the new ``ep_size`` this is a
    plain re-placement onto ``moe_partition_specs``; when it does not
    (e.g. 6 experts onto ep=4 after losing a host), the stack is padded to
    the next multiple (:func:`pad_experts_for_ep`) and sharded evenly.
    Returns ``(params, info)`` where ``info["num_experts_logical"]`` must
    be forwarded to the gating call whenever ``info["padded"]`` is true.
    """
    topo = topology or get_topology()
    ep = int(topo.dims[EXPERT])
    gate_key = "gate" if "gate" in params else "router"
    E = int(params[gate_key]["kernel"].shape[1])
    params, E_logical = pad_experts_for_ep(params, ep)
    info = {"num_experts_logical": E_logical,
            "num_experts_padded": int(params[gate_key]["kernel"].shape[1]),
            "ep_size": ep, "padded": E_logical !=
            int(params[gate_key]["kernel"].shape[1]),
            # the rows each rank ACTUALLY holds (even chunks of the padded
            # stack, clipped to logical experts) — not the ideal balanced
            # split, which padding cannot realize
            "shard_ranges": placed_expert_ranges(E, ep)}
    specs = moe_partition_specs()
    placed = {}
    for key, sub in params.items():
        spec_sub = specs.get(key) if key in ("gate", "experts") else None
        placed[key] = {}
        for name, arr in sub.items():
            if key == "experts" or key.endswith("_proj"):
                spec = P(EXPERT, *([None] * (arr.ndim - 1)))
            elif spec_sub is not None and name in spec_sub:
                spec = spec_sub[name]
            else:
                spec = P(*([None] * arr.ndim))
            placed[key][name] = jax.device_put(
                arr, jax.sharding.NamedSharding(topo.mesh, spec))
    return placed, info
