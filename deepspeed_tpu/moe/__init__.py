from .layer import MoE
from .sharded_moe import (
    init_moe_params,
    moe_layer,
    moe_partition_specs,
    top1gating,
    top2gating,
    topkgating,
)

__all__ = ["MoE", "moe_layer", "init_moe_params", "moe_partition_specs",
           "top1gating", "top2gating", "topkgating"]
