"""Jaxpr detectors: each encodes a miscompile / NaN-poisoning bug class
this repo has already paid for at runtime (the motivating PR is named on
every pass).  All passes walk the full nested jaxpr via
``jaxpr_walk.iter_eqns`` and attach ``file:line`` provenance from eqn
source info, so the ``# dstpu-check: disable=<pass>`` pragma on the traced
source line can allowlist a deliberate exception.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..utils.jaxpr_utils import _is_leaf_eqn, _sub_jaxprs
from .core import (ERROR, WARN, Finding, GraphPass, PassContext,
                   register_pass, relpath)
from .jaxpr_walk import (COLLECTIVE_PRIMS, LAYOUT_PRIMS, WIRE_LAYOUT_PRIMS,
                         as_jaxpr, chase, describe_eqn, eqn_site, iter_eqns,
                         value_graph)

_REPLICATED = "rep"
_SHARDED = "shard"

#: primitives GSPMD may rewrite into per-replica-group operations when the
#: operand is sharded (the PR-8/9 miscompile class)
_GROUP_REWRITE_PRIMS = ("gather", "dynamic_slice", "dynamic_update_slice")

#: value-preserving ops sharding knowledge propagates through (compute ops
#: let GSPMD re-decide placement — knowledge stops there, conservatively)
_SHARDING_PROP = frozenset({
    "reshape", "transpose", "convert_element_type", "squeeze",
    "expand_dims", "copy", "broadcast_in_dim",
})

_COMPARISONS = frozenset({"eq", "ne", "lt", "le", "gt", "ge", "is_finite"})
_BOOL_COMBINE = frozenset({"and", "or", "not", "xor"})

#: mask producer chains run through these before the multiply
_MASK_CHAIN = frozenset({
    "broadcast_in_dim", "reshape", "convert_element_type", "transpose",
    "expand_dims", "squeeze", "copy",
})

_COLLECTIVE_PRIMS = COLLECTIVE_PRIMS
_WIRE_LAYOUT = WIRE_LAYOUT_PRIMS


def _classify_sharding(s) -> Optional[str]:
    """Sharding object → replicated / sharded / unknown(None)."""
    if s is None:
        return None
    try:
        if bool(getattr(s, "is_fully_replicated")):
            return _REPLICATED
    except Exception:  # noqa: BLE001 — e.g. UnspecifiedValue
        return None
    mesh = getattr(s, "mesh", None)
    if mesh is not None and getattr(mesh, "size", 0) <= 1:
        return _REPLICATED
    return _SHARDED


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


def _shared_graph(closed, ctx: PassContext):
    """The run-level value graph when :func:`~.core.run_graph_passes`
    built one for this exact program, else a fresh build (direct pass
    invocation, e.g. ``assert_fused_pack``)."""
    cached = ctx.extra.get("value_graph")
    if cached is not None and cached[0] is closed:
        return cached[1]
    return value_graph(closed)


@register_pass
class ReplicaGroupGatherPass(GraphPass):
    """gather/dynamic-slice/scatter over a *sharded* operand outside a
    manual ``shard_map`` region.

    Bug class: GSPMD partitions the op per shard and psums the partial
    results over EVERY replica group — including pure data-replica groups —
    so the result comes back multiplied by the replica-group count.
    Observed twice: PR 8 ``paged_kv_append`` row-scatter cached K/V exactly
    4x on a dp4×tp2 mesh; PR 9 ``combine_sparse``'s ``jnp.take`` scaled MoE
    output by the data-axis size.  Fix idiom: pin the operand replicated
    (``with_sharding_constraint``, see ``moe/sharded_moe._pin_replicated``
    and ``paged_kv_append(replicate=)``) or move the op inside a manual
    ``shard_map`` region where GSPMD cannot rewrite it.

    Sharding knowledge comes from ``sharding_constraint`` eqns in the
    trace, pjit in_shardings, and ``ctx.arg_shardings``; it propagates
    through layout ops only (after real compute GSPMD re-decides placement,
    so the pass stays silent — no false positives on unknown shardings).
    """

    name = "replica-group-gather"
    severity = ERROR
    bug_class = ("GSPMD per-replica-group rewrite of gather/scatter over a "
                 "sharded operand (PR 8 paged_kv_append, PR 9 "
                 "combine_sparse)")

    def run(self, closed, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        top = as_jaxpr(closed)
        seeds: Dict[object, str] = {}
        if ctx.arg_shardings:
            for v, s in zip(top.invars, ctx.arg_shardings):
                st = _classify_sharding(s)
                if st is not None:
                    seeds[v] = st
        self._walk(top, seeds, False, ctx, findings)
        return findings

    # ---- dataflow over one jaxpr level ---------------------------------
    def _walk(self, jx, seeds: Dict[object, str], in_shard_map: bool,
              ctx: PassContext, findings: List[Finding]) -> None:
        state: Dict[object, str] = dict(seeds)

        def get(v) -> Optional[str]:
            if _is_literal(v):
                return _REPLICATED
            return state.get(v)

        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "sharding_constraint":
                st = _classify_sharding(eqn.params.get("sharding"))
                if st is not None:
                    for ov in eqn.outvars:
                        state[ov] = st
                continue
            if (name in _GROUP_REWRITE_PRIMS or name.startswith("scatter")) \
                    and not in_shard_map and eqn.invars:
                if get(eqn.invars[0]) == _SHARDED:
                    f, ln = eqn_site(eqn)
                    findings.append(self.finding(
                        f"{name} over a sharded operand outside a manual "
                        f"shard_map region — GSPMD may rewrite this into "
                        f"per-replica-group ops and sum the groups' partial "
                        f"results (PR-8/9 miscompile class); pin the operand "
                        f"replicated with with_sharding_constraint or move "
                        f"it inside shard_map",
                        file=relpath(f), line=ln, eqn=describe_eqn(eqn),
                        ctx=ctx))
            if name in _SHARDING_PROP and eqn.invars:
                st = get(eqn.invars[0])
                if st is not None:
                    for ov in eqn.outvars:
                        state[ov] = st
            elif name == "concatenate":
                sts = {get(v) for v in eqn.invars}
                if len(sts) == 1 and None not in sts:
                    for ov in eqn.outvars:
                        state[ov] = sts.pop()
            # ---- recursion ---------------------------------------------
            if _is_leaf_eqn(eqn):
                continue
            inner_sm = in_shard_map or name == "shard_map"
            if name == "pjit":
                cj = eqn.params.get("jaxpr")
                inner = getattr(cj, "jaxpr", cj)
                if inner is not None and hasattr(inner, "invars"):
                    sub_seeds: Dict[object, str] = {}
                    in_sh = eqn.params.get("in_shardings") or ()
                    for i, iv in enumerate(inner.invars):
                        st = get(eqn.invars[i]) if i < len(eqn.invars) \
                            else None
                        if st is None and i < len(in_sh):
                            st = _classify_sharding(in_sh[i])
                        if st is not None:
                            sub_seeds[iv] = st
                    self._walk(inner, sub_seeds, inner_sm, ctx, findings)
                    continue
            for sub in _sub_jaxprs(eqn):
                # scan/while/cond/custom_* bodies: no positional seed
                # mapping attempted — unknown-in, conservative
                self._walk(sub, {}, inner_sm, ctx, findings)


@register_pass
class MaskedNaNPass(GraphPass):
    """Multiply-by-mask over memory that can hold garbage/NaN.

    Bug class: ``mask * v`` where ``mask`` is a (broadcast of a)
    comparison and ``v`` was gathered/sliced from a buffer whose unused
    slots are uninitialized — ``0 × NaN = NaN``, so one poisoned padding
    slot NaNs the whole row.  Fixed three times in this repo (PR 6
    ``decode_attend_dense``, PR 8 ``_attend_gather``, PR 10's ragged
    verify kernel): the correct idiom is select-BEFORE-multiply
    (``jnp.where(mask, v, 0)``), which this pass recognizes as clean
    (the chase stops at ``select_n``).
    """

    name = "masked-nan-propagation"
    severity = ERROR
    bug_class = ("0×NaN through mask-multiply of gathered padding slots "
                 "(fixed in _attend_gather, decode_attend_dense, and the "
                 "PR-10 ragged kernel)")

    def run(self, closed, ctx: PassContext) -> List[Finding]:
        graph = _shared_graph(closed, ctx)
        findings: List[Finding] = []
        for info in iter_eqns(closed):
            eqn = info.eqn
            if eqn.primitive.name != "mul" or len(eqn.invars) != 2:
                continue
            a, b = eqn.invars
            for mask_v, val_v in ((a, b), (b, a)):
                if not self._mask_like(mask_v, graph):
                    continue
                origin = self._garbage_origin(val_v, graph)
                if origin is None:
                    continue
                f, ln = eqn_site(eqn)
                findings.append(self.finding(
                    f"mask-multiply over values read by "
                    f"{origin.primitive.name} — padding/unused slots can "
                    f"hold garbage and 0×NaN=NaN poisons the row; "
                    f"select-before-multiply instead "
                    f"(jnp.where(mask, v, 0))",
                    file=relpath(f), line=ln, eqn=describe_eqn(eqn),
                    ctx=ctx))
                break
        return findings

    def _mask_like(self, v, graph) -> bool:
        if getattr(getattr(v, "aval", None), "dtype", None) == bool:
            return True
        origin, _ = chase(v, graph, _MASK_CHAIN)
        if origin is None:
            return False
        name = origin.primitive.name
        return name in _COMPARISONS or name in _BOOL_COMBINE

    def _garbage_origin(self, v, graph):
        """The gather/dynamic_slice this value was read by, or None when a
        select_n (the fixed idiom) or any compute sits in between.  The
        *read buffer* must be a program input (KV pages, expert stacks —
        memory whose unused slots nobody initialized); a gather over
        freshly-computed values (e.g. log-probs in the loss mask) is
        defined everywhere and stays clean."""
        origin, _ = chase(v, graph, LAYOUT_PRIMS)
        if origin is None or \
                origin.primitive.name not in ("gather", "dynamic_slice"):
            return None
        if not origin.invars:
            return None
        src, terminal = chase(origin.invars[0], graph, LAYOUT_PRIMS)
        if src is None and terminal is not None and \
                hasattr(terminal, "count"):
            return origin
        return None


@register_pass
class FusedWireLayoutPass(GraphPass):
    """Quantized-collective wire contract (generalizes PR 9's
    ``assert_fused_pack``): every int8-operand collective must consume the
    output of a Pallas quantize+pack kernel through layout-only ops —
    any arithmetic in between means the pack fell out of the kernel and a
    full-precision intermediate is materialized on the wire path (the
    legacy strided int4 nibble pack is the historical offender).  Also
    flags duplicate collectives over the same operand (warn): the same
    tensor exchanged twice is paid-for bandwidth."""

    name = "fused-wire-layout"
    severity = ERROR
    bug_class = ("unfused quantize→exchange wire (PR 9: legacy jnp int4 "
                 "pack between quantize and collective)")

    #: collectives checked under the fused-gemm expectation: the epilogue
    #: exchanges (reduce-scatter family + the quantized a2a wire).  The
    #: prologue's all_gather is exempt — its operand is the raw weight
    #: shard, a program input with no producer to fuse.
    GEMM_COLLECTIVES = ("reduce_scatter", "psum_scatter", "all_to_all")

    def run(self, closed, ctx: PassContext) -> List[Finding]:
        import jax.numpy as jnp

        graph = _shared_graph(closed, ctx)
        findings: List[Finding] = []
        seen: Dict[tuple, int] = {}
        # fused-gemm edge contract (PR 15, T3 arXiv:2401.16677): on
        # artifacts traced with ctx.extra["expect_fused_gemm"], EVERY
        # epilogue-family collective operand — any dtype, not just the
        # int8 wire — must chase through layout-only ops to the producing
        # pallas_call; the unfused matmul→psum_scatter composition is the
        # tested negative control (fixtures.py)
        expect_gemm = bool(ctx.extra.get("expect_fused_gemm"))
        gemm_prims = tuple(ctx.extra.get("fused_gemm_collectives",
                                         self.GEMM_COLLECTIVES))
        for info in iter_eqns(closed):
            eqn = info.eqn
            name = eqn.primitive.name
            if not any(name.startswith(p) for p in _COLLECTIVE_PRIMS):
                continue
            if expect_gemm and eqn.invars and \
                    any(name.startswith(p) for p in gemm_prims):
                findings.extend(self._check_gemm_edge(eqn, graph, ctx))
            if eqn.invars:
                key = (name, id(eqn.invars[0]))
                seen[key] = seen.get(key, 0) + 1
                if seen[key] == 2:
                    f, ln = eqn_site(eqn)
                    findings.append(self.finding(
                        f"duplicate {name} over the same operand — the "
                        f"same tensor is exchanged twice",
                        file=relpath(f), line=ln, eqn=describe_eqn(eqn),
                        ctx=ctx, severity=WARN))
            wire = next((v for v in eqn.invars
                         if getattr(getattr(v, "aval", None), "dtype", None)
                         == jnp.int8), None)
            if wire is None:
                continue
            findings.extend(self._check_wire(eqn, wire, graph, ctx))
        return findings

    def _check_gemm_edge(self, eqn, graph, ctx) -> List[Finding]:
        """Epilogue collective under the fused-gemm expectation: operand
        must be the producing Pallas kernel's output (through layout ops).
        A program-input operand stays clean — there was no producer to
        fuse (the degenerate leaf-seam edge)."""
        origin, terminal = chase(eqn.invars[0], graph, _WIRE_LAYOUT)
        if origin is not None and origin.primitive.name == "pallas_call":
            return []
        if origin is None:
            return []          # program input / literal — nothing unfused
        f, ln = eqn_site(origin)
        return [self.finding(
            f"fused-gemm edge: {eqn.primitive.name} operand produced by "
            f"{origin.primitive.name!r} instead of the fused matmul "
            f"pallas_call — the collective fell out of the producing "
            f"kernel (unfused matmul→collective composition); use "
            f"kernels/fused_collective_matmul.matmul_reduce_scatter",
            file=relpath(f), line=ln, eqn=describe_eqn(origin), ctx=ctx)]

    def _check_wire(self, eqn, v, graph, ctx) -> List[Finding]:
        origin, _hops = chase(v, graph, _WIRE_LAYOUT)
        if origin is not None and origin.primitive.name == "pallas_call":
            return []
        if origin is not None:
            f, ln = eqn_site(origin)
            return [self.finding(
                f"int8 wire operand of {eqn.primitive.name} produced "
                f"through non-layout op {origin.primitive.name!r} — pack "
                f"is not fused into the quant kernel",
                file=relpath(f), line=ln, eqn=describe_eqn(origin),
                ctx=ctx)]
        f, ln = eqn_site(eqn)
        return [self.finding(
            f"int8 wire operand of {eqn.primitive.name} does not "
            f"originate from a Pallas quant+pack kernel",
            file=relpath(f), line=ln, eqn=describe_eqn(eqn), ctx=ctx)]


@register_pass
class GatherBudgetPass(GraphPass):
    """``all-gather`` count vs the caller's budget (scan trip counts
    multiplied).  Bug class: the PR-4 weight-prefetch invariant — with
    ``GatherWindowCache`` active the per-micro-batch program must carry
    ZERO param all-gathers (they moved to the once-per-window gather fn);
    a regression here silently re-pays (gas-1) gathers per window.  Runs
    only when ``ctx.gather_budget`` is set."""

    name = "gather-budget"
    severity = ERROR
    bug_class = ("per-micro all_gather leak under GatherWindowCache "
                 "(PR 4 prefetch invariant)")

    def run(self, closed, ctx: PassContext) -> List[Finding]:
        if ctx.gather_budget is None:
            return []
        total = 0.0
        sites = []
        for info in iter_eqns(closed):
            if info.eqn.primitive.name.startswith("all_gather"):
                total += info.mult
                if len(sites) < 4:
                    f, ln = eqn_site(info.eqn)
                    sites.append(f"{relpath(f)}:{ln}")
        count = int(round(total))
        if count <= ctx.gather_budget:
            return []
        return [self.finding(
            f"{count} all-gather eqn(s) (scan-multiplied) exceed the "
            f"budget of {ctx.gather_budget} for this program — e.g. the "
            f"prefetched per-micro step must carry none (PR-4 "
            f"GatherWindowCache invariant); first sites: "
            f"{', '.join(sites)}",
            file=None, line=None, ctx=ctx)]
