"""Source-level (AST) detectors + the walker that feeds them.

Consolidates the two standalone lints (``tools/check_no_bare_print.py``,
``tools/check_no_bare_except.py``) into the pass registry — their CLI entry
points now delegate here — and adds the trace-hygiene classes that can only
be caught at the source level: import-time ``jnp`` computation (initializes
the XLA backend before ``apply_xla_flags`` can set ``LIBTPU_INIT_ARGS`` —
the PR-4 flag-wiring hazard), jitted entry points taking Python scalars in
shape-relevant positions (retrace explosions, historically guarded only by
per-test ``trace_counts`` probes), and host-sync calls inside step-loop /
decode-window code paths (a per-iteration D2H round trip was the measured
3 tok/s decode regression PR 6 removed).

All passes honor ``# dstpu-check: disable=<pass>`` on the offending line;
the bare-print pass additionally keeps its historical ``# lint:
allow-print`` marker so existing allowlists stay valid.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import List, Optional, Sequence, Set, Tuple

from .core import (ERROR, WARN, Finding, SourcePass, pragma_disables,
                   register_pass, relpath)

# --------------------------------------------------------------------- #
# Parsed-file carrier
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class SourceFile:
    path: str
    text: str
    lines: List[str]
    tree: Optional[ast.Module]
    syntax_error: Optional[Tuple[int, str]] = None

    @classmethod
    def parse(cls, path: str) -> "SourceFile":
        with open(path, "rb") as f:
            raw = f.read()
        text = raw.decode("utf-8", "replace")
        try:
            tree = ast.parse(raw, filename=path)
            return cls(path, text, text.splitlines(), tree)
        except SyntaxError as e:
            return cls(path, text, text.splitlines(), None,
                       syntax_error=(e.lineno or 0, e.msg or "syntax error"))

    def jnp_aliases(self) -> Set[str]:
        """Local names bound to ``jax.numpy`` (``jnp`` by idiom)."""
        aliases = {"jnp"}
        if self.tree is None:
            return aliases
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.numpy" and a.asname:
                        aliases.add(a.asname)
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
        return aliases


def _attr_chain(expr) -> List[str]:
    """``jax.numpy.zeros`` → ['jax', 'numpy', 'zeros']; [] when the base is
    not a plain name."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return parts[::-1]
    return []


def _names_in(expr) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


# --------------------------------------------------------------------- #
# bare-print (tools/check_no_bare_print.py logic, registry-hosted)
# --------------------------------------------------------------------- #
ALLOW_PRINT_MARKER = "lint: allow-print"

#: functions whose body (incl. nested defs) may print: CLI entry points and
#: the flops profiler's single audited report-output seam
PRINTING_FUNC_NAMES = frozenset({"main", "emit_report"})


def _main_guard_lines(tree: ast.Module) -> Set[int]:
    lines: Set[int] = set()
    for node in tree.body:
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if (isinstance(test, ast.Compare) and isinstance(test.left, ast.Name)
                and test.left.id == "__name__"):
            end = getattr(node, "end_lineno", node.lineno)
            lines.update(range(node.lineno, end + 1))
    return lines


def bare_print_offenders(sf: SourceFile) -> List[Tuple[int, str]]:
    """(line, why) offenders — the exact semantics the standalone lint has
    enforced since PR 2 (main()/__main__-guard/emit_report exempt,
    ``# lint: allow-print`` per-line allowlist)."""
    if sf.tree is None:
        return []
    allowed = {i + 1 for i, line in enumerate(sf.lines)
               if ALLOW_PRINT_MARKER in line}
    allowed |= _main_guard_lines(sf.tree)
    offenders: List[Tuple[int, str]] = []

    def walk(node, in_main: bool):
        for child in ast.iter_child_nodes(node):
            child_in_main = in_main
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_in_main = in_main or child.name in PRINTING_FUNC_NAMES
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id == "print"
                    and not in_main
                    and child.lineno not in allowed):
                offenders.append((child.lineno, "bare print"))
            walk(child, child_in_main)

    walk(sf.tree, in_main=False)
    return offenders


@register_pass
class BarePrintPass(SourcePass):
    """Library output must go through utils.logging or telemetry; a stray
    ``print`` spams every rank and is invisible to the run summary (see
    tools/check_no_bare_print.py for the full contract)."""

    name = "bare-print"
    severity = ERROR
    bug_class = "un-capturable per-rank stdout spam (PR 2 logging contract)"

    def run(self, sf: SourceFile) -> List[Finding]:
        return [self.finding(
            "bare print in library code — use utils.logging / telemetry, "
            "or move CLI output into main()",
            file=relpath(sf.path), line=line)
            for line, _why in bare_print_offenders(sf)]


def bare_except_offenders(sf: SourceFile) -> List[Tuple[int, str]]:
    if sf.tree is None:
        return []
    return [(node.lineno, "bare except")
            for node in ast.walk(sf.tree)
            if isinstance(node, ast.ExceptHandler) and node.type is None]


@register_pass
class BareExceptPass(SourcePass):
    """A bare except swallows KeyboardInterrupt/SystemExit and hides the
    storage/transport errors the fault subsystem exists to surface."""

    name = "bare-except"
    severity = ERROR
    bug_class = "fault paths swallowed by bare except (PR 1 fault contract)"

    def run(self, sf: SourceFile) -> List[Finding]:
        return [self.finding(
            "bare except — use 'except Exception:' or narrower so fault "
            "paths stay visible",
            file=relpath(sf.path), line=line)
            for line, _why in bare_except_offenders(sf)]


# --------------------------------------------------------------------- #
# import-time jnp computation
# --------------------------------------------------------------------- #
@register_pass
class ImportTimeJnpPass(SourcePass):
    """No ``jnp.``/``jax.numpy`` computation at module import time.

    Bug class: an import-time op initializes the XLA backend BEFORE
    ``deepspeed_tpu.initialize()`` runs ``apply_xla_flags`` — so
    ``LIBTPU_INIT_ARGS`` (the PR-4 latency-hiding-scheduler flags) is read
    too late and silently ignored for the whole process.  Flags module- and
    class-level calls plus default-argument expressions of module/class-
    level functions (defaults evaluate at import).  Constants belong inside
    the traced function or behind a lazy/cached accessor.
    """

    name = "import-time-jnp"
    severity = ERROR
    bug_class = ("backend initialized before apply_xla_flags could set "
                 "LIBTPU_INIT_ARGS (PR 4 flag wiring)")

    def run(self, sf: SourceFile) -> List[Finding]:
        if sf.tree is None:
            return []
        aliases = sf.jnp_aliases()
        findings: List[Finding] = []

        def is_jnp_call(call: ast.Call) -> bool:
            chain = _attr_chain(call.func)
            if not chain:
                return False
            return chain[0] in aliases or \
                (len(chain) >= 2 and chain[0] == "jax"
                 and chain[1] == "numpy")

        def flag(call: ast.Call, where: str) -> None:
            findings.append(self.finding(
                f"jnp computation at import time ({where}) — initializes "
                f"the XLA backend before apply_xla_flags can set "
                f"LIBTPU_INIT_ARGS; build it lazily inside the function",
                file=relpath(sf.path), line=call.lineno))

        def scan(node, where: str) -> None:
            """Import-time-executed statements of one module/class body."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    for d in list(child.args.defaults) + \
                            [kd for kd in child.args.kw_defaults if kd]:
                        for sub in ast.walk(d):
                            if isinstance(sub, ast.Call) and \
                                    is_jnp_call(sub):
                                flag(sub, f"default arg of {child.name}()")
                    continue   # body runs at call time, not import
                if isinstance(child, ast.Lambda):
                    continue
                if isinstance(child, ast.ClassDef):
                    scan(child, f"class {child.name} body")
                    continue
                if isinstance(child, ast.Call) and is_jnp_call(child):
                    flag(child, where)
                scan(child, where)

        scan(sf.tree, "module level")
        return findings


# --------------------------------------------------------------------- #
# retrace-hazard
# --------------------------------------------------------------------- #
_SHAPE_FUNCS = frozenset({
    "zeros", "ones", "full", "empty", "arange", "eye", "tile",
    "broadcast_to", "linspace", "reshape",
})


def _jit_decorator_info(dec) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when ``dec`` is a jax.jit form
    (``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``), else None."""
    def is_jit_ref(expr) -> bool:
        chain = _attr_chain(expr)
        return chain in (["jit"], ["jax", "jit"])

    call = None
    if is_jit_ref(dec):
        return set(), set()
    if isinstance(dec, ast.Call):
        chain = _attr_chain(dec.func)
        if is_jit_ref(dec.func):
            call = dec
        elif chain and chain[-1] == "partial" and dec.args and \
                is_jit_ref(dec.args[0]):
            call = dec
    if call is None:
        return None
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        vals = kw.value.elts if isinstance(
            kw.value, (ast.Tuple, ast.List)) else [kw.value]
        if kw.arg == "static_argnames":
            names |= {v.value for v in vals
                      if isinstance(v, ast.Constant)
                      and isinstance(v.value, str)}
        elif kw.arg == "static_argnums":
            nums |= {v.value for v in vals
                     if isinstance(v, ast.Constant)
                     and isinstance(v.value, int)}
    return names, nums


@register_pass
class RetraceHazardPass(SourcePass):
    """Jitted entry points taking Python scalars in shape-relevant
    positions: every distinct value is a fresh trace + XLA compile.

    Bug class: the retrace explosions only the per-test ``trace_counts``
    probes have guarded so far — the sanctioned idioms are the compile-
    cache bucket tables (``bucket_tokens``/``bucket_for``) or
    ``static_argnums``/``static_argnames``.  Flags a non-static parameter
    of a ``@jax.jit`` function used inside a shape-constructing call
    (``jnp.zeros((n,))``, ``x.reshape(n, -1)``) or as a Python loop bound
    (``range(n)`` additionally unrolls the loop into the trace).
    """

    name = "retrace-hazard"
    severity = WARN
    bug_class = ("per-value retrace of jitted fns taking Python scalars "
                 "in shape positions (trace_counts probe class)")

    def run(self, sf: SourceFile) -> List[Finding]:
        if sf.tree is None:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            info = None
            for dec in node.decorator_list:
                info = _jit_decorator_info(dec)
                if info is not None:
                    break
            if info is None:
                continue
            static_names, static_nums = info
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            dynamic = {p for i, p in enumerate(params)
                       if p not in static_names and i not in static_nums
                       and p != "self"}
            if not dynamic:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                used = self._shape_use(sub, dynamic)
                if used:
                    findings.append(self.finding(
                        f"jitted function {node.name}() uses Python "
                        f"argument(s) {sorted(used)} in a shape position — "
                        f"every distinct value retraces; mark static_"
                        f"argnums/static_argnames or route through a "
                        f"bucket table",
                        file=relpath(sf.path), line=sub.lineno))
        return findings

    def _shape_use(self, call: ast.Call, dynamic: Set[str]) -> Set[str]:
        chain = _attr_chain(call.func)
        shapeish = bool(chain) and chain[-1] in _SHAPE_FUNCS
        loopish = isinstance(call.func, ast.Name) and \
            call.func.id == "range"
        if not (shapeish or loopish):
            return set()
        used: Set[str] = set()
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            used |= _names_in(arg) & dynamic
        return used


# --------------------------------------------------------------------- #
# host-sync
# --------------------------------------------------------------------- #
_HOT_FUNC_RE = re.compile(r"(decode|verify|train_batch|window|micro|step)")


@register_pass
class HostSyncPass(SourcePass):
    """Per-iteration device→host syncs inside step-loop / decode-window
    code paths.

    Bug class: the measured 3 tok/s decode (PR 6) — a host round trip per
    decode step dominated wall time until sampling moved on-device and the
    loop became a fused scan.  Flags, inside ``for``/``while`` bodies of
    functions whose name matches step/decode/verify/window/micro,
    ``.item()``, ``jax.device_get(...)``, and ``float``/``int`` applied
    directly to a ``jnp`` computation — each is a blocking transfer per
    iteration.  Window-boundary drains (one sync per window, not per step)
    belong OUTSIDE the loop or behind a ``# dstpu-check:
    disable=host-sync`` pragma naming why the sync is sanctioned.
    """

    name = "host-sync"
    severity = WARN
    bug_class = ("per-step D2H sync in the decode loop (PR 6's measured "
                 "3 tok/s host-driven decode)")

    def run(self, sf: SourceFile) -> List[Finding]:
        if sf.tree is None:
            return []
        aliases = sf.jnp_aliases()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _HOT_FUNC_RE.search(node.name):
                continue
            for loop in ast.walk(node):
                if not isinstance(loop, (ast.For, ast.While,
                                         ast.AsyncFor)):
                    continue
                for sub in ast.walk(loop):
                    if isinstance(sub, ast.Call):
                        why = self._sync_call(sub, aliases)
                        if why:
                            findings.append(self.finding(
                                f"{why} inside a loop of {node.name}() — "
                                f"one blocking device→host transfer per "
                                f"iteration; batch the sync at the window "
                                f"boundary or keep the value on device",
                                file=relpath(sf.path), line=sub.lineno))
        return findings

    def _sync_call(self, call: ast.Call, aliases: Set[str]) -> Optional[str]:
        chain = _attr_chain(call.func)
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "item" and not call.args:
            return ".item()"
        if chain and chain[-1] == "device_get":
            return "jax.device_get"
        if isinstance(call.func, ast.Name) and \
                call.func.id in ("float", "int") and len(call.args) == 1:
            for sub in ast.walk(call.args[0]):
                if isinstance(sub, ast.Call):
                    inner = _attr_chain(sub.func)
                    if inner and (inner[0] in aliases or
                                  inner[0] == "jax"):
                        return f"{call.func.id}() on a jnp value"
        return None


# --------------------------------------------------------------------- #
# Runner
# --------------------------------------------------------------------- #
def iter_py_files(roots: Sequence[str]):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for d, _dirs, fns in os.walk(root):
            if "__pycache__" in d:
                continue
            for fn in sorted(fns):
                if fn.endswith(".py"):
                    yield os.path.join(d, fn)


def run_source_passes(roots: Sequence[str],
                      passes: Optional[Sequence[SourcePass]] = None,
                      ) -> List[Finding]:
    """All (or the given) source passes over every ``.py`` under ``roots``;
    unparseable files produce one error-severity ``syntax-error`` finding.
    Pragma filtering happens against the freshly-read file content."""
    from .core import all_passes
    ps = list(passes) if passes is not None else all_passes("source")
    findings: List[Finding] = []
    for path in sorted(set(iter_py_files(roots))):
        sf = SourceFile.parse(path)
        if sf.syntax_error is not None:
            line, msg = sf.syntax_error
            findings.append(Finding("syntax-error", ERROR,
                                    f"syntax error: {msg}",
                                    file=relpath(path), line=line))
            continue
        for p in ps:
            for f in p.run(sf):
                if f.line and 0 < f.line <= len(sf.lines) and \
                        pragma_disables(sf.lines[f.line - 1], f.pass_name):
                    continue
                findings.append(f)
    return findings
