"""Recursive jaxpr traversal shared by the graph passes.

Generalizes the walk ``utils/jaxpr_utils`` does for flop attribution:
every eqn is visited with its static execution multiplicity (scan trip
counts multiplied through nesting, while bodies count one trip — an
explicit undercount) and with a flag saying whether it sits inside a
``shard_map`` manual region (where per-device collectives/gathers are
hand-written and GSPMD cannot rewrite them — several passes exempt those).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional, Tuple

from ..utils.jaxpr_utils import _is_leaf_eqn, _sub_jaxprs


def as_jaxpr(traced):
    """``jax.make_jaxpr`` result / ClosedJaxpr / raw jaxpr → raw jaxpr."""
    j = traced
    while hasattr(j, "jaxpr"):
        j = j.jaxpr
    if not hasattr(j, "eqns"):
        raise TypeError(f"not a jaxpr: {type(traced).__name__}")
    return j


@dataclasses.dataclass
class EqnInfo:
    eqn: object
    #: static execution count (scan trip counts multiplied through nesting)
    mult: float
    #: inside a shard_map body (manual region — GSPMD keeps its hands off)
    in_shard_map: bool
    #: nesting depth (0 = top level)
    depth: int


def iter_eqns(traced) -> Iterator[EqnInfo]:
    """Every eqn of ``traced`` and its sub-jaxprs (pjit/scan/cond/while/
    remat/custom_vjp/pallas bodies), scalar-combiner sub-jaxprs excluded —
    same conventions as the profiler's cost walk."""
    def walk(jx, mult: float, in_sm: bool, depth: int):
        for eqn in jx.eqns:
            yield EqnInfo(eqn, mult, in_sm, depth)
            if _is_leaf_eqn(eqn):
                continue
            inner_mult = mult
            if eqn.primitive.name == "scan":
                inner_mult *= float(eqn.params.get("length", 1))
            inner_sm = in_sm or eqn.primitive.name == "shard_map"
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub, inner_mult, inner_sm, depth + 1)

    yield from walk(as_jaxpr(traced), 1.0, False, 0)


def eqn_site(eqn) -> Tuple[Optional[str], Optional[int]]:
    """Best-effort (file, line) of the user source that emitted ``eqn`` —
    the provenance findings carry and the pragma filter resolves."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return None, None
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(si)
        if frame is not None:
            line = getattr(frame, "start_line", None) or \
                getattr(frame, "line_num", None)
            return frame.file_name, int(line) if line else None
    except Exception:  # noqa: BLE001 — provenance is best-effort by design
        pass
    return None, None


def describe_eqn(eqn) -> str:
    """Short eqn description for finding text: primitive + operand avals."""
    def aval_str(v):
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            return "?"
        return f"{getattr(aval, 'dtype', '?')}{list(aval.shape)}"

    ins = ",".join(aval_str(v) for v in eqn.invars[:3])
    more = ",…" if len(eqn.invars) > 3 else ""
    return f"{eqn.primitive.name}({ins}{more})"


#: container primitives whose eqn invars/outvars map POSITIONALLY onto the
#: sub-jaxpr's invars/outvars, so a producer chase can cross the boundary
#: (scan: consts+carry+xs in / carry+ys out — positional either side;
#: cond/while have multiple bodies or split signatures and are excluded)
_ALIASING_CONTAINERS = frozenset({
    "pjit", "closed_call", "core_call", "remat", "remat2", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "shard_map", "scan",
})


def value_graph(traced) -> Tuple[Dict, Dict, Dict]:
    """(producers, out_alias, in_alias) across every nesting level.

    ``producers``: var → producing eqn.  ``out_alias``: a container eqn's
    outvar → the sub-jaxpr outvar it forwards.  ``in_alias``: a sub-jaxpr
    invar → the outer eqn invar bound to it.  Together these let
    :func:`chase` follow a value through pjit/remat/custom_vjp/shard_map/
    scan boundaries instead of stopping at the call eqn.
    """
    producers: Dict[object, object] = {}
    out_alias: Dict[object, object] = {}
    in_alias: Dict[object, object] = {}

    def handle(jx):
        for eqn in jx.eqns:
            for v in eqn.outvars:
                producers[v] = eqn
            if _is_leaf_eqn(eqn):
                continue
            subs = list(_sub_jaxprs(eqn))
            if eqn.primitive.name in _ALIASING_CONTAINERS and len(subs) == 1:
                inner = subs[0]
                if len(inner.invars) == len(eqn.invars):
                    for iv, ov in zip(inner.invars, eqn.invars):
                        in_alias[iv] = ov
                if len(inner.outvars) == len(eqn.outvars):
                    for outer_ov, inner_ov in zip(eqn.outvars, inner.outvars):
                        out_alias[outer_ov] = inner_ov
            for sub in subs:
                handle(sub)

    handle(as_jaxpr(traced))
    return producers, out_alias, in_alias


def chase(var, graph, through: frozenset, max_hops: int = 64):
    """Follow ``var`` back through producer eqns whose primitive is in
    ``through`` (first operand only — layout chains are unary), crossing
    container boundaries via the :func:`value_graph` aliases.

    Returns (origin_eqn_or_None, terminal_var_or_None): the first producer
    OUTSIDE ``through``, or — when the chain ends without one —
    the terminal value itself: a jaxpr invar/constvar (``Var``: a buffer
    fed INTO the program) or a ``Literal`` (an initialized constant).
    Exactly one of the two is non-None, except on hop exhaustion."""
    producers, out_alias, in_alias = graph
    hops = 0
    while hops < max_hops:
        if not hasattr(var, "count"):      # Literal — no producer
            return None, var
        if var in out_alias:               # container result → inner value
            var = out_alias[var]
            hops += 1
            continue
        eqn = producers.get(var)
        if eqn is None:
            if var in in_alias:            # sub-jaxpr arg → outer value
                var = in_alias[var]
                hops += 1
                continue
            return None, var               # program input / constvar
        if eqn.primitive.name not in through:
            return eqn, None
        if not eqn.invars:
            return eqn, None
        var = eqn.invars[0]
        hops += 1
    return None, None


#: pure layout/dtype ops: value-preserving reshapes a producer chain may
#: run through without "computing" anything
LAYOUT_PRIMS = frozenset({
    "reshape", "transpose", "squeeze", "expand_dims", "broadcast_in_dim",
    "convert_element_type", "copy", "slice", "rev",
})

#: collective primitive name prefixes — the ONE definition shared by the
#: fused-wire pass, ``runtime/comm/fused_wire.wire_ops``, and
#: ``assert_quantized_wire`` (a primitive added to one consumer but not
#: another would make the CI gate and the in-test assertion disagree)
COLLECTIVE_PRIMS = ("all_to_all", "all_gather", "psum", "reduce_scatter")

#: the fused-wire contract: between a quantize kernel and its collective
#: nothing but these may sit (narrower than LAYOUT_PRIMS: no slice/rev —
#: the wire must consume the pack's bytes whole)
WIRE_LAYOUT_PRIMS = frozenset({
    "reshape", "transpose", "squeeze", "expand_dims", "broadcast_in_dim",
    "convert_element_type",
})
