"""Static-analysis pass framework (``dstpu-check``) core.

Three PRs in a row root-caused the *same* bug classes after the fact —
GSPMD rewriting a gather/scatter over a sharded operand into per-replica-
group scatter-adds (PR 8 ``paged_kv_append``, PR 9 ``combine_sparse``),
0×NaN padding poisoning through unused block-table slots (fixed three
times: ``_attend_gather``, ``decode_attend_dense``, the ragged kernel),
and retrace explosions guarded only by per-test ``trace_counts`` probes.
This module is the correctness-tooling layer the paper's runtime-only
debugging story lacks: each recurring class becomes a *detector* that runs
over traced jaxprs (``graph_passes``) or source ASTs (``source_passes``)
at trace time / in CI, instead of being re-bisected on silicon.

Vocabulary:

  * :class:`Finding` — one detector hit, carrying ``file:line`` / eqn
    provenance and a severity (``error`` fails the CI gate, ``warn`` and
    ``advice`` are reported only).
  * :class:`GraphPass` / :class:`SourcePass` — a detector.  Graph passes
    walk closed jaxprs (recursively, through scan/cond/while/pjit/
    custom_vjp sub-jaxprs, multiplying scan trip counts); source passes
    walk Python ASTs.
  * the registry (:func:`register_pass` / :func:`all_passes`) — the one
    list ``bin/dstpu-check``, the engine ``debug.graph_lint`` knob, and
    the fixture suite all consume.
  * allowlist pragmas — ``# dstpu-check: disable=<pass>[,<pass>|all]`` on
    the offending source line suppresses a finding (jaxpr findings
    resolve to the traced Python line via eqn provenance, so the pragma
    works for both kinds).
"""
from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

ERROR = "error"
WARN = "warn"
ADVICE = "advice"

#: severity rank: higher sorts first in reports; only ERROR gates CI
_SEVERITY_RANK = {ERROR: 0, WARN: 1, ADVICE: 2}

_PRAGMA_RE = re.compile(r"#\s*dstpu-check:\s*disable=([\w\-,\s]+)")


class GraphLintError(RuntimeError):
    """Raised by the engine's ``debug.graph_lint: "error"`` mode when an
    error-severity finding survives pragma filtering."""


@dataclasses.dataclass
class Finding:
    """One detector hit with provenance.

    ``file``/``line`` point at the Python source that produced the flagged
    construct (the traced line for jaxpr passes, the AST node for source
    passes); ``eqn`` carries the jaxpr-level description (primitive name +
    operand summary) when the finding came from a graph pass; ``artifact``
    names which built program was being linted (train step, decode bucket,
    fused wire, ...).
    """

    pass_name: str
    severity: str
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    eqn: Optional[str] = None
    artifact: Optional[str] = None

    def where(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "<no provenance>"
        art = f" [{self.artifact}]" if self.artifact else ""
        return loc + art

    def render(self) -> str:
        eqn = f" ({self.eqn})" if self.eqn else ""
        return (f"{self.where()}: {self.severity}: {self.pass_name}: "
                f"{self.message}{eqn}")


@dataclasses.dataclass
class PassContext:
    """Everything a graph pass may need beyond the jaxpr itself.

    ``artifact``       — name of the built program under analysis.
    ``mesh``           — the live mesh, when the caller has one (passes
                         must not require it: sharding objects embedded in
                         the jaxpr carry their own mesh).
    ``arg_shardings``  — optional per-invar shardings for the top-level
                         jaxpr (the engine knows its param shardings; a
                         bare ``make_jaxpr`` trace does not).
    ``gather_budget``  — max ``all-gather`` eqns allowed (scan-multiplied);
                         ``None`` disables the gather-budget pass.  The
                         PR-4 prefetch invariant is budget 0 on the
                         pregathered per-micro program.
    """

    artifact: str = "<unnamed>"
    mesh: Any = None
    arg_shardings: Optional[Sequence[Any]] = None
    gather_budget: Optional[int] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


class GraphPass:
    """Base for jaxpr detectors.  Subclasses set ``name``, ``severity``,
    ``bug_class`` (one line: the historical bug this encodes) and implement
    ``run(jaxpr, ctx) -> List[Finding]`` over a *closed* jaxpr."""

    name: str = "<abstract>"
    severity: str = ERROR
    bug_class: str = ""

    def run(self, closed, ctx: PassContext) -> List[Finding]:
        raise NotImplementedError

    def finding(self, message: str, *, file=None, line=None, eqn=None,
                ctx: Optional[PassContext] = None,
                severity: Optional[str] = None) -> Finding:
        return Finding(self.name, severity or self.severity, message,
                       file=file, line=line, eqn=eqn,
                       artifact=ctx.artifact if ctx else None)


class SourcePass:
    """Base for AST detectors.  ``run(sf) -> List[Finding]`` over a parsed
    :class:`~.source_passes.SourceFile`."""

    name: str = "<abstract>"
    severity: str = ERROR
    bug_class: str = ""

    def run(self, sf) -> List[Finding]:
        raise NotImplementedError

    def finding(self, message: str, *, file=None, line=None,
                severity: Optional[str] = None) -> Finding:
        return Finding(self.name, severity or self.severity, message,
                       file=file, line=line)


# --------------------------------------------------------------------- #
# Registry
# --------------------------------------------------------------------- #
_REGISTRY: Dict[str, Any] = {}


def register_pass(p):
    """Register a pass instance (or class — instantiated immediately).
    Usable as a decorator on the class.  Re-registration under the same
    name replaces (reload-friendly)."""
    inst = p() if isinstance(p, type) else p
    _REGISTRY[inst.name] = inst
    return p


def get_pass(name: str):
    if name not in _REGISTRY:
        raise KeyError(f"unknown dstpu-check pass {name!r}; registered: "
                       f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]


def all_passes(kind: Optional[str] = None) -> List[Any]:
    """Registered passes, optionally filtered: ``kind='jaxpr'`` → graph
    passes, ``kind='source'`` → AST passes."""
    out = []
    for name in sorted(_REGISTRY):
        p = _REGISTRY[name]
        if kind == "jaxpr" and not isinstance(p, GraphPass):
            continue
        if kind == "source" and not isinstance(p, SourcePass):
            continue
        out.append(p)
    return out


# --------------------------------------------------------------------- #
# Pragma allowlist
# --------------------------------------------------------------------- #
_FILE_LINE_CACHE: Dict[str, List[str]] = {}


def _source_lines(path: str) -> List[str]:
    if path not in _FILE_LINE_CACHE:
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                _FILE_LINE_CACHE[path] = f.read().splitlines()
        except OSError:
            _FILE_LINE_CACHE[path] = []
    return _FILE_LINE_CACHE[path]


def pragma_disables(line_text: str, pass_name: str) -> bool:
    """True when ``line_text`` carries ``# dstpu-check: disable=`` naming
    ``pass_name`` (or ``all``)."""
    m = _PRAGMA_RE.search(line_text)
    if not m:
        return False
    names = {n.strip() for n in m.group(1).split(",")}
    return "all" in names or pass_name in names


def filter_pragmas(findings: Sequence[Finding]) -> List[Finding]:
    """Drop findings whose provenance line carries a disabling pragma."""
    kept = []
    for f in findings:
        if f.file and f.line:
            lines = _source_lines(f.file)
            if 0 < f.line <= len(lines) and \
                    pragma_disables(lines[f.line - 1], f.pass_name):
                continue
        kept.append(f)
    return kept


# --------------------------------------------------------------------- #
# Runners + report
# --------------------------------------------------------------------- #
def run_graph_passes(traced, ctx: PassContext,
                     passes: Optional[Sequence[GraphPass]] = None,
                     ) -> List[Finding]:
    """All (or the given) graph passes over one traced program, pragma-
    filtered.  ``traced`` is a ``jax.make_jaxpr`` result, a ClosedJaxpr,
    or a raw jaxpr.  The producer/alias graph is built ONCE here and
    shared via ``ctx.extra["value_graph"]`` — several passes chase
    producer chains and a large scanned train step should not pay the
    full-jaxpr walk per pass."""
    from .jaxpr_walk import value_graph

    cached = ctx.extra.get("value_graph")
    if cached is None or cached[0] is not traced:   # ctx reuse = rebuild
        ctx.extra["value_graph"] = (traced, value_graph(traced))
    findings: List[Finding] = []
    for p in (passes if passes is not None else all_passes("jaxpr")):
        findings.extend(p.run(traced, ctx))
    return filter_pragmas(findings)


def max_severity(findings: Sequence[Finding]) -> Optional[str]:
    if not findings:
        return None
    return min((f.severity for f in findings),
               key=lambda s: _SEVERITY_RANK.get(s, 99))


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (
        _SEVERITY_RANK.get(f.severity, 99), f.pass_name,
        f.file or "", f.line or 0))


def summarize(findings: Sequence[Finding],
              artifacts: Optional[Sequence[str]] = None) -> str:
    """Prometheus-style summary block: one ``dstpu_check_findings`` series
    per (pass, severity) — including zero series for every registered pass
    so a clean run is visibly clean — plus the artifact sweep count."""
    counts: Dict[Tuple[str, str], int] = {}
    for f in findings:
        counts[(f.pass_name, f.severity)] = \
            counts.get((f.pass_name, f.severity), 0) + 1
    lines = ["# TYPE dstpu_check_findings gauge"]
    default_sev = {p.name: p.severity for p in all_passes()}
    # every registered pass gets a (zero) series so a clean run is visibly
    # clean — PLUS any finding name outside the registry (e.g. the
    # runner's "syntax-error"), which must never vanish from the summary
    names = sorted(set(default_sev) | {f.pass_name for f in findings})
    for name in names:
        sevs = {s for (n, s) in counts if n == name} or \
            {default_sev.get(name, ERROR)}
        for sev in sorted(sevs):
            lines.append(
                f'dstpu_check_findings{{pass="{name}",severity="{sev}"}} '
                f'{counts.get((name, sev), 0)}')
    if artifacts is not None:
        lines.append("# TYPE dstpu_check_artifacts gauge")
        lines.append(f"dstpu_check_artifacts {len(artifacts)}")
    return "\n".join(lines)


def relpath(path: Optional[str]) -> Optional[str]:
    """Repo-relative path when possible (stable finding rendering)."""
    if not path:
        return path
    try:
        rel = os.path.relpath(path)
        return rel if not rel.startswith("..") else path
    except ValueError:
        return path
