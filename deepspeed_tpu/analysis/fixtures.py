"""Historical-bug fixtures: every detector's bug class, deliberately
re-introduced, so the gate can assert each pass still FIRES (a linter
whose detectors rot is worse than none — it certifies broken code clean).

Used by ``tools/check_graph_lint.py`` and the tier-1 fixture suite
(``tests/unit/test_graph_lint.py``).  Each builder returns a traced
program (or source text for AST passes) reproducing the original bug
pattern as closely as the tiny CPU sim allows:

  * ``unpinned_sharded_gather``  — PR 8/9: ``jnp.take`` over a
    tensor-sharded operand on a dp4×tp2 mesh, no replicated pin.
  * ``nan_mask_multiply``        — PR 6/8/10: mask-multiply over values
    gathered from a page pool, select-AFTER-multiply.
  * ``legacy_unfused_int4_wire`` — PR 9: the jnp-composed strided int4
    nibble pack between quantize and collective.
  * ``all_gather_in_micro``      — PR 4: an all-gather inside the
    (supposedly prefetched) per-micro program.
  * source snippets for import-time-jnp / retrace-hazard / host-sync /
    bare-print / bare-except.
"""
from __future__ import annotations

from .core import PassContext


def unpinned_sharded_gather():
    """(traced, ctx): the PR-8/9 replica-group miscompile pattern — a
    gather whose operand is pinned TENSOR-sharded (not replicated) on a
    dp4×tp2 mesh, outside any shard_map."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..runtime.topology import TENSOR, TopologyConfig, initialize_mesh

    topo = initialize_mesh(TopologyConfig(tensor=2), force=True)
    sharded = NamedSharding(topo.mesh, P(TENSOR, None))

    def bad(table, idx):
        t = jax.lax.with_sharding_constraint(table, sharded)
        return jnp.take(t, idx, axis=0)

    traced = jax.make_jaxpr(bad)(
        jax.ShapeDtypeStruct((8, 4), jnp.float32),
        jax.ShapeDtypeStruct((3,), jnp.int32))
    return traced, PassContext(artifact="fixture:unpinned_sharded_gather",
                               mesh=topo.mesh)


def pinned_replicated_gather():
    """The FIXED idiom for the same pattern (``_pin_replicated`` /
    ``paged_kv_append(replicate=)``): identical gather, operand pinned
    fully replicated — must stay clean."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..runtime.topology import TopologyConfig, initialize_mesh

    topo = initialize_mesh(TopologyConfig(tensor=2), force=True)
    replicated = NamedSharding(topo.mesh, P())

    def good(table, idx):
        t = jax.lax.with_sharding_constraint(table, replicated)
        return jnp.take(t, idx, axis=0)

    traced = jax.make_jaxpr(good)(
        jax.ShapeDtypeStruct((8, 4), jnp.float32),
        jax.ShapeDtypeStruct((3,), jnp.int32))
    return traced, PassContext(artifact="fixture:pinned_replicated_gather",
                               mesh=topo.mesh)


def nan_mask_multiply():
    """(traced, ctx): the thrice-fixed 0×NaN class — rows gathered from a
    page pool multiplied by a padding mask AFTER the read, so a garbage/
    NaN slot rides ``0×NaN=NaN`` into the output."""
    import jax
    import jax.numpy as jnp

    def bad(pages, idx, ctx_len):
        v = jnp.take(pages, idx, axis=0)          # page-pool read
        mask = (jnp.arange(v.shape[0]) < ctx_len).astype(v.dtype)
        return v * mask[:, None]                  # select-AFTER-multiply

    traced = jax.make_jaxpr(bad)(
        jax.ShapeDtypeStruct((16, 4), jnp.float32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32))
    return traced, PassContext(artifact="fixture:nan_mask_multiply")


def select_before_multiply():
    """The FIXED idiom: ``jnp.where(mask, v, 0)`` before any multiply —
    must stay clean."""
    import jax
    import jax.numpy as jnp

    def good(pages, idx, ctx_len):
        v = jnp.take(pages, idx, axis=0)
        mask = jnp.arange(v.shape[0]) < ctx_len
        v = jnp.where(mask[:, None], v, 0.0)
        return v * 2.0

    traced = jax.make_jaxpr(good)(
        jax.ShapeDtypeStruct((16, 4), jnp.float32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32))
    return traced, PassContext(artifact="fixture:select_before_multiply")


def legacy_unfused_int4_wire():
    """(traced, ctx): PR 9's negative control — the legacy jnp-composed
    int4 wire whose strided nibble pack (an ``or`` of shifted slices) sits
    between the quantize and the collective."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..runtime.comm_path import quantized_allreduce
    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)

    def ex(x):
        out, _, _ = quantized_allreduce(x[0], (DATA,), bits=4, fused=False)
        return out[None]

    n = topo.mesh.shape[DATA]
    traced = jax.make_jaxpr(compat_shard_map(
        ex, topo.mesh, (P(DATA),), P(DATA), manual_axes={DATA}))(
            jax.ShapeDtypeStruct((n, 40, 8), jnp.float32))
    return traced, PassContext(artifact="fixture:legacy_unfused_int4_wire",
                               mesh=topo.mesh)


def unfused_matmul_psum_scatter():
    """(traced, ctx): the fused-gemm negative control — a plain
    ``jnp.dot`` whose result feeds ``psum_scatter`` (the unfused
    matmul→collective composition), linted under the
    ``expect_fused_gemm`` contract the PR-15 epilogue artifacts carry."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)
    n = topo.mesh.shape[DATA]

    def bad(x, w):
        y = jnp.dot(x[0], w, preferred_element_type=jnp.float32)
        part = jax.lax.psum_scatter(y, DATA, scatter_dimension=0,
                                    tiled=True)
        return (part / n)[None]

    traced = jax.make_jaxpr(compat_shard_map(
        bad, topo.mesh, (P(DATA), P()), P(DATA), manual_axes={DATA}))(
            jax.ShapeDtypeStruct((n, 8 * n, 16), jnp.float32),
            jax.ShapeDtypeStruct((16, 32), jnp.float32))
    return traced, PassContext(
        artifact="fixture:unfused_matmul_psum_scatter", mesh=topo.mesh,
        extra={"expect_fused_gemm": True})


def fused_gemm_epilogue():
    """The FIXED idiom: the reduce-scatter epilogue matmul
    (``kernels/fused_collective_matmul.matmul_reduce_scatter``) — the
    collective's operand IS the shard-major Pallas kernel's output — must
    stay clean under the same expectation."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..kernels.fused_collective_matmul import matmul_reduce_scatter
    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)
    n = topo.mesh.shape[DATA]

    def good(x, w):
        return matmul_reduce_scatter(x[0], w, (DATA,), impl="pallas")[None]

    traced = jax.make_jaxpr(compat_shard_map(
        good, topo.mesh, (P(DATA), P()), P(DATA), manual_axes={DATA}))(
            jax.ShapeDtypeStruct((n, 8 * n, 16), jnp.float32),
            jax.ShapeDtypeStruct((16, 32), jnp.float32))
    return traced, PassContext(artifact="fixture:fused_gemm_epilogue",
                               mesh=topo.mesh,
                               extra={"expect_fused_gemm": True})


def all_gather_in_micro():
    """(traced, ctx): the PR-4 prefetch-invariant violation — a param
    all-gather inside a per-micro program linted with gather_budget=0."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)

    def micro(w_shard):
        full = jax.lax.all_gather(w_shard, DATA)   # leaked into the micro
        return (full * full).sum()

    n = topo.mesh.shape[DATA]
    traced = jax.make_jaxpr(compat_shard_map(
        micro, topo.mesh, (P(DATA),), P(), manual_axes={DATA}))(
            jax.ShapeDtypeStruct((n, 16), "float32"))
    return traced, PassContext(artifact="fixture:all_gather_in_micro",
                               gather_budget=0)


# --------------------------------------------------------------------- #
# Source-pass fixtures (text → write to a tmp file, run the AST passes)
# --------------------------------------------------------------------- #
SOURCE_FIXTURES = {
    "import-time-jnp": (
        "import jax.numpy as jnp\n"
        "PAD_ROW = jnp.zeros((4,))        # initializes the backend\n"
        "def f(x, scale=jnp.float32(2.0) * jnp.ones(())):\n"
        "    return x\n"
    ),
    "retrace-hazard": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def pad_to(x, n):\n"
        "    return jnp.concatenate([x, jnp.zeros((n,))])\n"
    ),
    "host-sync": (
        "import numpy as np\n"
        "def decode_window(engine, steps):\n"
        "    out = []\n"
        "    for _ in range(steps):\n"
        "        tok = engine.step_once()\n"
        "        out.append(tok.item())\n"
        "    return out\n"
    ),
    "bare-print": (
        "def helper(x):\n"
        "    print('value', x)\n"
        "    return x\n"
    ),
    "bare-except": (
        "def helper(x):\n"
        "    try:\n"
        "        return x()\n"
        "    except:\n"
        "        return None\n"
    ),
}


def run_source_fixture(pass_name: str, tmp_dir: str):
    """Write the named source fixture into ``tmp_dir`` and run ONLY that
    pass over it; returns the findings."""
    import os

    from .core import get_pass
    from .source_passes import run_source_passes

    path = os.path.join(tmp_dir, f"fixture_{pass_name.replace('-', '_')}.py")
    with open(path, "w", encoding="utf-8") as f:
        f.write(SOURCE_FIXTURES[pass_name])
    return run_source_passes([path], passes=[get_pass(pass_name)])


#: graph-pass fixture table: key → (firing builder, clean builder).  A key
#: is a pass name, optionally suffixed ``:variant`` when one pass encodes
#: several bug classes (``fixture_pass_name`` strips the suffix) — the
#: fused-wire-layout pass carries both the PR-9 wire contract and the
#: PR-15 fused-gemm edge contract.
GRAPH_FIXTURES = {
    "replica-group-gather": (unpinned_sharded_gather,
                             pinned_replicated_gather),
    "masked-nan-propagation": (nan_mask_multiply, select_before_multiply),
    "fused-wire-layout": (legacy_unfused_int4_wire, None),
    "fused-wire-layout:gemm": (unfused_matmul_psum_scatter,
                               fused_gemm_epilogue),
    "gather-budget": (all_gather_in_micro, None),
}


def fixture_pass_name(key: str) -> str:
    """GRAPH_FIXTURES key → registered pass name (strips the ``:variant``
    suffix)."""
    return key.split(":", 1)[0]
