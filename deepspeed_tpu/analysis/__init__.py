"""``dstpu-check``: static analysis over traced jaxprs and source ASTs.

The correctness-tooling layer: recurring miscompile / NaN-poisoning /
trace-hygiene bug classes encoded as registered detectors.  Entry points:

  * ``bin/dstpu-check`` — CLI sweep over the actual built artifacts
    (train step, decode/verify buckets, fused wire) + source tree;
  * ``config.debug.graph_lint`` — engine knob: run the graph passes at
    first trace, emit ``analysis/*`` telemetry;
  * ``tools/check_graph_lint.py`` — the CI gate (HEAD clean, historical
    fixtures fire), enforced from tier-1.

Importing this package registers every built-in pass.
"""
from .core import (ADVICE, ERROR, WARN, Finding, GraphLintError, GraphPass,
                   PassContext, SourcePass, all_passes, filter_pragmas,
                   get_pass, max_severity, pragma_disables, register_pass,
                   run_graph_passes, sort_findings, summarize)
from . import graph_passes  # noqa: F401  — registers the jaxpr passes
from . import source_passes  # noqa: F401  — registers the AST passes
from .source_passes import SourceFile, run_source_passes  # noqa: F401

__all__ = [
    "ADVICE", "ERROR", "WARN", "Finding", "GraphLintError", "GraphPass",
    "PassContext", "SourcePass", "SourceFile", "all_passes",
    "filter_pragmas", "get_pass", "max_severity", "pragma_disables",
    "register_pass", "run_graph_passes", "run_source_passes",
    "sort_findings", "summarize",
]
