"""Build the *actual* production programs and hand their jaxprs to the
graph passes — the ``bin/dstpu-check`` sweep and the
``tools/check_graph_lint.py`` CI gate both run here.

"Actual" means the same builders the engines use, at tiny CPU-sim shapes:
the fused train step (``engine._build_train_batch_fn``), the PR-4
prefetched per-micro program (``comm_path.build_explicit_micro_fn``
— linted with ``gather_budget=0``, the GatherWindowCache invariant), the
serving prefill/decode/verify bucket programs
(``model_runner.build_ragged_step``/``build_decode_loop``/
``build_verify_step`` at the engine's real bucket shapes, both attention
impls), and the fused quantized collective wire
(``comm_path.quantized_allreduce`` under ``shard_map``).  Everything is
``jax.make_jaxpr`` only — no XLA compile — so the full sweep stays well
inside the 120 s gate budget.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from .core import Finding, PassContext, run_graph_passes


@dataclasses.dataclass
class Artifact:
    name: str
    traced: object            # jax.make_jaxpr result
    ctx: PassContext


def _struct_of(tree):
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


# --------------------------------------------------------------------- #
# Serving engine buckets
# --------------------------------------------------------------------- #
def build_inference_artifacts(attn_impl: str = "gather",
                              ) -> List[Artifact]:
    """Prefill / fused-decode / spec-dec-verify programs of a tiny
    ``InferenceEngineV2`` at its real bucket shapes.  ``gather`` is the
    XLA lowering (the numerics oracle — fully analyzable); ``paged``
    additionally walks the Pallas kernel body."""
    import jax
    import jax.numpy as jnp

    from ..inference.v2.engine_v2 import (InferenceEngineV2,
                                          RaggedInferenceEngineConfig)
    from ..inference.v2.model_runner import (build_decode_loop,
                                             build_ragged_step,
                                             build_verify_step)
    from ..inference.v2.ragged.ragged_wrapper import pack_layout
    from ..models.transformer import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(use_flash=False)
    model = CausalLM(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        max_tokens=16, max_seqs=4, max_ctx=64, block_size=8,
        dtype=jnp.float32, attn_impl=attn_impl, block_q=16,
        pages_per_chunk=2))
    c = eng.config
    params_struct = _struct_of(eng.params)
    pages = eng.kv.pages
    pages_struct = jax.ShapeDtypeStruct(pages.shape, pages.dtype)
    # real leaf shardings seed the replica-group pass (invar order:
    # params leaves, pages, meta[, rng] — matching make_jaxpr flattening)
    param_shardings = [getattr(leaf, "sharding", None)
                       for leaf in jax.tree.leaves(eng.params)]

    def arg_shardings(with_rng=False):
        return param_shardings + [getattr(pages, "sharding", None), None] \
            + ([None] if with_rng else [])

    def meta_struct(key):
        n = pack_layout(key[0], key[1],
                        eng._wrapper_for(key).max_blocks)["_total"][0]
        return jax.ShapeDtypeStruct((n,), jnp.int32)

    def common(key):
        return dict(num_blocks=eng._num_blocks, attn_impl=c.attn_impl,
                    max_seqs=key[1],
                    max_blocks=eng._wrapper_for(key).max_blocks,
                    block_q=c.block_q, pages_per_chunk=c.pages_per_chunk,
                    jit=False, kv_replicate=eng._kv_replicate)

    out: List[Artifact] = []
    # prefill bucket for an 8-token single-sequence put()
    pkey = eng.bucket_for(8, 1)
    step = build_ragged_step(eng.cfg, max_q=pkey[0], **common(pkey))
    out.append(Artifact(
        f"prefill[{attn_impl},bucket={pkey}]",
        jax.make_jaxpr(step)(params_struct, pages_struct,
                             meta_struct(pkey)),
        PassContext(artifact=f"prefill[{attn_impl}]",
                    arg_shardings=arg_shardings())))

    # fused decode window: 2 sequences, 4 steps, greedy
    s_b = eng._seq_bucket(2)
    dkey = (s_b, s_b)
    loop = build_decode_loop(
        eng.cfg, max_q=dkey[0], max_seqs=dkey[1],
        max_blocks=eng._wrapper_for(dkey).max_blocks,
        block_size=c.block_size, num_blocks=eng._num_blocks,
        attn_impl=c.attn_impl, steps=4, temperature=0.0,
        block_q=c.block_q, pages_per_chunk=c.pages_per_chunk,
        top_k=0, jit=False, kv_replicate=eng._kv_replicate)
    rng_struct = _struct_of(jax.random.PRNGKey(0))
    out.append(Artifact(
        f"decode_loop[{attn_impl},bucket={dkey},steps=4]",
        jax.make_jaxpr(loop)(params_struct, pages_struct,
                             meta_struct(dkey), rng_struct),
        PassContext(artifact=f"decode_loop[{attn_impl}]",
                    arg_shardings=arg_shardings(with_rng=True))))

    # spec-dec verify window at the same bucket
    vstep = build_verify_step(eng.cfg, max_q=dkey[0], **common(dkey))
    out.append(Artifact(
        f"verify[{attn_impl},bucket={dkey}]",
        jax.make_jaxpr(vstep)(params_struct, pages_struct,
                              meta_struct(dkey)),
        PassContext(artifact=f"verify[{attn_impl}]",
                    arg_shardings=arg_shardings())))
    return out


# --------------------------------------------------------------------- #
# Training step (fused scan path)
# --------------------------------------------------------------------- #
def _tiny_train_engine(config_overrides: Optional[Dict] = None,
                       gas: int = 2):
    import jax

    import deepspeed_tpu
    from ..models.transformer import CausalLM, TransformerConfig
    from ..runtime.topology import TopologyConfig, initialize_mesh

    topo = initialize_mesh(TopologyConfig(), force=True)
    cfg = TransformerConfig.tiny(use_flash=False)
    model = CausalLM(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
    }
    config.update(config_overrides or {})
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config, topology=topo)
    return eng, topo


def _batch_struct(eng, gas: int):
    import jax
    import jax.numpy as jnp

    micro_global = eng.train_micro_batch_size_per_gpu() * \
        max(eng.topology.get_data_parallel_world_size(), 1)
    shape = (gas, micro_global, 32) if gas > 1 else (micro_global, 32)
    return {"input_ids": jax.ShapeDtypeStruct(shape, jnp.int32)}


def build_train_artifact() -> Artifact:
    """The fused train step (scan over micro-batches + optimizer update)
    exactly as ``train_batch`` would jit it, with the engine's real state
    shardings seeding the replica-group pass."""
    import jax

    gas = 2
    eng, topo = _tiny_train_engine(gas=gas)
    fn = eng._build_train_batch_fn()
    state_struct = _struct_of(eng.state)
    batch = _batch_struct(eng, gas)
    traced = jax.make_jaxpr(fn)(state_struct, batch)
    shardings = [getattr(leaf, "sharding", None)
                 for leaf in jax.tree.leaves(eng.state)]
    shardings += [None] * len(jax.tree.leaves(batch))
    ctx = PassContext(artifact="train_step[zero2,gas=2]", mesh=topo.mesh,
                      arg_shardings=shardings)
    return Artifact(ctx.artifact, traced, ctx)


def build_prefetch_artifact() -> Artifact:
    """The PR-4 invariant program: the *pregathered* explicit-comm
    per-micro step under stage-3 quantized weight gather — must carry
    ZERO all-gathers (``gather_budget=0``); the once-per-window gather fn
    owns the wire."""
    import jax

    from ..runtime.comm_path import (build_explicit_micro_fn,
                                     build_param_gather_fn,
                                     make_explicit_grad_acc)

    eng, topo = _tiny_train_engine(
        gas=2,
        config_overrides={
            "zero_optimization": {"stage": 3, "zero_quantized_weights": True,
                                  "stage3_param_persistence_threshold": 0},
            "bf16": {"enabled": True},
            "overlap": {"enabled": True, "prefetch_params": True},
        })
    # the explicit path accumulates LOCAL per-data-shard grads (leading
    # [n_dp] axis) — mirror backward()'s lazy re-layout before tracing
    state = eng.state.replace(grad_acc=make_explicit_grad_acc(eng))
    gathered_struct = jax.eval_shape(build_param_gather_fn(eng),
                                     _struct_of(state.params))
    micro = build_explicit_micro_fn(eng, pregathered=True)
    traced = jax.make_jaxpr(micro)(_struct_of(state),
                                   _batch_struct(eng, gas=1),
                                   gathered_struct)
    ctx = PassContext(artifact="micro_pregathered[zero3,qwZ]",
                      mesh=topo.mesh, gather_budget=0)
    return Artifact(ctx.artifact, traced, ctx)


# --------------------------------------------------------------------- #
# Fused quantized collective wire
# --------------------------------------------------------------------- #
def build_fused_wire_artifact(bits: int = 4) -> Artifact:
    """The production fused quantize→exchange→dequantize allreduce traced
    under a full-manual shard_map on the 8-device sim mesh — the EQuARX
    wire the fused-wire-layout pass protects."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..runtime.comm_path import quantized_allreduce
    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)

    def ex(x):
        out, _, _ = quantized_allreduce(x[0], (DATA,), bits=bits)
        return out[None]

    n = topo.mesh.shape[DATA]
    stacked = jax.ShapeDtypeStruct((n, 40, 8), jnp.float32)
    traced = jax.make_jaxpr(compat_shard_map(
        ex, topo.mesh, (P(DATA),), P(DATA), manual_axes={DATA}))(stacked)
    return Artifact(f"fused_wire[int{bits}]", traced,
                    PassContext(artifact=f"fused_wire[int{bits}]",
                                mesh=topo.mesh))


# --------------------------------------------------------------------- #
# Fused compute+collective matmul edges (PR 15, T3)
# --------------------------------------------------------------------- #
def build_fused_gemm_artifact(wire_bits: int = 0) -> Artifact:
    """The reduce-scatter epilogue matmul traced under shard_map on the
    8-device sim, linted with ``expect_fused_gemm``: every epilogue
    collective operand must chase to the producing pallas_call — the
    contract the fused-wire-layout pass's gemm extension enforces (the
    unfused matmul→psum_scatter composition is the fixture negative
    control)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..kernels.fused_collective_matmul import matmul_reduce_scatter
    from ..runtime.topology import (DATA, TopologyConfig, compat_shard_map,
                                    initialize_mesh)

    topo = initialize_mesh(TopologyConfig(), force=True)
    n = topo.mesh.shape[DATA]

    def ex(x, w):
        return matmul_reduce_scatter(x[0], w, (DATA,),
                                     wire_bits=wire_bits,
                                     impl="pallas")[None]

    traced = jax.make_jaxpr(compat_shard_map(
        ex, topo.mesh, (P(DATA), P()), P(DATA), manual_axes={DATA}))(
            jax.ShapeDtypeStruct((n, 8 * n, 32), jnp.float32),
            jax.ShapeDtypeStruct((32, 64), jnp.float32))
    wire = f"int{wire_bits}" if wire_bits else "fp"
    name = f"fused_gemm_epilogue[{wire}]"
    return Artifact(name, traced,
                    PassContext(artifact=name, mesh=topo.mesh,
                                extra={"expect_fused_gemm": True}))


# --------------------------------------------------------------------- #
# The sweep
# --------------------------------------------------------------------- #
_BUILDERS: Dict[str, Callable[[], List[Artifact]]] = {
    "inference": lambda: (build_inference_artifacts("gather") +
                          build_inference_artifacts("paged")),
    "train": lambda: [build_train_artifact()],
    "prefetch": lambda: [build_prefetch_artifact()],
    "fused_wire": lambda: [build_fused_wire_artifact(4),
                           build_fused_wire_artifact(8)],
    "fused_gemm": lambda: [build_fused_gemm_artifact(0),
                           build_fused_gemm_artifact(8)],
}


def builder_names() -> List[str]:
    return sorted(_BUILDERS)


def sweep(only: Optional[Sequence[str]] = None,
          log: Optional[Callable[[str], None]] = None,
          ):
    """Build every artifact group (or ``only`` the named ones) and run all
    graph passes over each.  Returns (findings, artifact_names)."""
    findings: List[Finding] = []
    names: List[str] = []
    for group in (only if only else builder_names()):
        if group not in _BUILDERS:
            raise KeyError(f"unknown artifact group {group!r}; known: "
                           f"{builder_names()}")
        for art in _BUILDERS[group]():
            if log is not None:
                log(f"lint {art.name}")
            findings.extend(run_graph_passes(art.traced, art.ctx))
            names.append(art.name)
    return findings, names
