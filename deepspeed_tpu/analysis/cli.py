"""``bin/dstpu-check`` — run the static-analysis passes from the shell.

Two sweeps, both on by default:

  * ``--graphs`` — build the actual artifacts on the CPU sim (train step,
    prefetched micro program, serving prefill/decode/verify buckets under
    both attention impls, fused quantized wire) and run every registered
    jaxpr pass over each (``analysis/artifacts.py``).
  * ``--source`` — run every registered AST pass over the library tree
    (default root: ``deepspeed_tpu/``).

Findings print one per line with ``file:line`` provenance, followed by a
prometheus-style summary (``dstpu_check_findings{pass=...,severity=...}``).
Exit status: 0 when no error-severity findings, 1 otherwise (warn/advice
never gate), 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import (ERROR, GraphPass, all_passes, sort_findings, summarize)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dstpu-check",
        description="Static analysis over traced jaxprs (miscompile / "
                    "NaN-poisoning detectors) and source ASTs (trace "
                    "hygiene).  No flags = both sweeps.")
    p.add_argument("--graphs", nargs="*", metavar="GROUP", default=None,
                   help="jaxpr sweep only; optional artifact groups "
                        "(default: all — see --list)")
    p.add_argument("--source", nargs="*", metavar="ROOT", default=None,
                   help="AST sweep only; optional roots "
                        "(default: the deepspeed_tpu/ package)")
    p.add_argument("--list", action="store_true",
                   help="list registered passes + artifact groups and exit")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of text")
    return p


def _list() -> str:
    from . import artifacts

    lines = ["registered passes (severity · kind · bug class):"]
    for p in all_passes():
        kind = "jaxpr" if isinstance(p, GraphPass) else "source"
        lines.append(f"  {p.name:<24} {p.severity:<7} {kind:<7} "
                     f"{p.bug_class}")
    lines.append("artifact groups (--graphs): " +
                 ", ".join(artifacts.builder_names()))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    if args.list:
        print(_list())
        return 0

    run_graphs = args.graphs is not None or args.source is None
    run_source = args.source is not None or args.graphs is None

    findings = []
    artifact_names: List[str] = []
    if run_graphs:
        from . import artifacts

        try:
            fs, artifact_names = artifacts.sweep(
                only=args.graphs or None,
                log=None if args.json else
                lambda m: print(f"dstpu-check: {m}", file=sys.stderr))
        except KeyError as e:
            print(f"dstpu-check: {e.args[0]}", file=sys.stderr)
            return 2
        findings.extend(fs)
    if run_source:
        from .source_passes import run_source_passes

        roots = args.source or [os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))]
        findings.extend(run_source_passes(roots))

    findings = sort_findings(findings)
    errors = [f for f in findings if f.severity == ERROR]
    if args.json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "artifacts": artifact_names,
            "errors": len(errors),
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(summarize(findings,
                        artifacts=artifact_names if run_graphs else None))
        verdict = "CLEAN" if not errors else f"{len(errors)} error(s)"
        print(f"dstpu-check: {len(findings)} finding(s), {verdict}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
