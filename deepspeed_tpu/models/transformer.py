"""TPU-native causal-LM transformer — the framework's flagship model family.

Reference analogue: the model containers DeepSpeed injects/serves
(``module_inject/containers/llama.py``, ``inference/v2/model_implementations/
llama_v2``) — but built as a first-class JAX model rather than a wrapper over
HF torch modules.

Design points (TPU-first):
  * stacked layer parameters + ``lax.scan`` over layers → O(1) compile time,
    XLA-friendly static control flow;
  * bf16 compute / fp32 master handled by the engine; this module computes in
    the dtype of the incoming params;
  * Megatron-style tensor-parallel sharding expressed as PartitionSpecs
    (``partition_specs``): qkv/gate/up kernels column-sharded over "tensor",
    o/down row-sharded; embeddings sharded over the hidden dim;
  * activation sharding constraints at layer boundaries: [batch→data axes,
    seq→"seq", hidden→None] so XLA lays out collectives over the right axes;
  * GQA (num_kv_heads ≤ num_heads), RoPE, RMSNorm, SwiGLU — the Llama recipe;
  * optional ``jax.checkpoint`` (remat) per layer for activation checkpointing.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.topology import DATA, DATA_OUTER, EXPERT, SEQ, TENSOR


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    intermediate_size: int = 1408
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: int = 8
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    #: bias on q/k/v projections (qwen2-family); o_proj stays bias-free
    attn_bias: bool = False
    remat: bool = False
    #: jax.checkpoint_policies name: "nothing_saveable" = full recompute
    #: (min memory); "dots_with_no_batch_dims_saveable" keeps matmul outputs
    #: (≈no recompute flops — the MFU-vs-memory dial)
    remat_policy: str = "nothing_saveable"
    use_flash: bool = True          # pallas flash attention on TPU
    attn_impl: str = "auto"         # auto | flash | xla | ring | ulysses
    #: flash kernel tile sizes; defaults from the on-chip sweep table
    #: (bench_logs r3: block_q=256/block_k=512 best on v5e at seq 2048)
    flash_block_q: int = 256
    flash_block_k: int = 512
    #: fold rms_norm into the consuming projections' Pallas kernels
    #: (``kernels/fused_collective_matmul.rmsnorm_matmul`` — the norm's
    #: variance/rsqrt recomputed per output tile, normalized activations
    #: never round-trip HBM).  "auto" = TPU only, so the CPU sim keeps the
    #: unfused jaxpr; "on"/"off" force it.  Bitwise vs the unfused
    #: composition under jit, test-asserted through the interpreter seam.
    fused_rmsnorm: str = "auto"   # auto | on | off
    # MoE (Mixtral-family): >1 experts replaces the dense MLP with a
    # top-k routed expert MLP on every layer.
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_loss_coef: float = 0.01
    moe_dispatch: str = "sparse"    # sparse (scatter, linear in tokens) | dense (oracle)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        return TransformerConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                                 num_layers=2, num_heads=4, num_kv_heads=2,
                                 max_seq_len=128, **kw)

    @staticmethod
    def tiny_moe(**kw):
        return TransformerConfig(vocab_size=256, hidden_size=64,
                                 intermediate_size=128, num_layers=2,
                                 num_heads=4, num_kv_heads=2, max_seq_len=128,
                                 num_experts=4, moe_top_k=2, **kw)

    @staticmethod
    def mixtral_8x7b(**kw):
        # 32k context (Mixtral's published window): the default sparse-slot
        # dispatch is linear in routing-chunk tokens, so long chunks no
        # longer materialize an O(S²·E/cf) dispatch tensor.
        return TransformerConfig(vocab_size=32000, hidden_size=4096,
                                 intermediate_size=14336, num_layers=32,
                                 num_heads=32, num_kv_heads=8, max_seq_len=32768,
                                 rope_theta=1e6, num_experts=8, moe_top_k=2, **kw)

    @staticmethod
    def llama3_8b(**kw):
        return TransformerConfig(vocab_size=128256, hidden_size=4096,
                                 intermediate_size=14336, num_layers=32,
                                 num_heads=32, num_kv_heads=8, max_seq_len=8192,
                                 rope_theta=500000.0, **kw)

    @staticmethod
    def gpt2_small(**kw):
        return TransformerConfig(vocab_size=50257, hidden_size=768,
                                 intermediate_size=3072, num_layers=12,
                                 num_heads=12, num_kv_heads=12, max_seq_len=1024, **kw)


# --------------------------------------------------------------------- #
# Parameter init + sharding specs
# --------------------------------------------------------------------- #
def init_params(cfg: TransformerConfig, key: jax.Array, dtype=jnp.float32) -> Dict:
    """Stacked-layer parameter pytree. Layer arrays have leading dim L."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def norm_init(*shape):
        return jnp.ones(shape, dtype)

    def dense_init(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": {"scale": norm_init(L, D)},
        "q_proj": {"kernel": dense_init(ks[0], (L, D, H * hd), D)},
        "k_proj": {"kernel": dense_init(ks[1], (L, D, KV * hd), D)},
        "v_proj": {"kernel": dense_init(ks[2], (L, D, KV * hd), D)},
        "o_proj": {"kernel": dense_init(ks[3], (L, H * hd, D), H * hd)},
        "mlp_norm": {"scale": norm_init(L, D)},
    }
    if cfg.attn_bias:
        layers["q_proj"]["bias"] = jnp.zeros((L, H * hd), dtype)
        layers["k_proj"]["bias"] = jnp.zeros((L, KV * hd), dtype)
        layers["v_proj"]["bias"] = jnp.zeros((L, KV * hd), dtype)
    if cfg.num_experts > 1:
        E = cfg.num_experts
        layers["router"] = {"kernel": dense_init(ks[7], (L, D, E), D).astype(jnp.float32)}
        layers["gate_proj"] = {"kernel": dense_init(ks[4], (L, E, D, F), D)}
        layers["up_proj"] = {"kernel": dense_init(ks[5], (L, E, D, F), D)}
        layers["down_proj"] = {"kernel": dense_init(ks[6], (L, E, F, D), F)}
    else:
        layers["gate_proj"] = {"kernel": dense_init(ks[4], (L, D, F), D)}
        layers["up_proj"] = {"kernel": dense_init(ks[5], (L, D, F), D)}
        layers["down_proj"] = {"kernel": dense_init(ks[6], (L, F, D), F)}
    params = {
        "embed": {"embedding": (jax.random.normal(k_embed, (cfg.vocab_size, D)) * 0.02).astype(dtype)},
        "layers": layers,
        "norm_f": {"scale": norm_init(D)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": dense_init(k_head, (D, cfg.vocab_size), D)}
    return params


def partition_specs(cfg: TransformerConfig) -> Dict:
    """Megatron-style TP specs (reference: module_inject/auto_tp.py row/col split).

    Column-parallel (output dim over "tensor"): q/k/v, gate, up.
    Row-parallel (input dim over "tensor"): o, down.  Embedding + lm_head
    sharded over the vocab/hidden as appropriate.
    """
    layer_specs = {
        "attn_norm": {"scale": P(None, None)},
        "q_proj": {"kernel": P(None, None, TENSOR)},
        "k_proj": {"kernel": P(None, None, TENSOR)},
        "v_proj": {"kernel": P(None, None, TENSOR)},
        "o_proj": {"kernel": P(None, TENSOR, None)},
        "mlp_norm": {"scale": P(None, None)},
    }
    if cfg.attn_bias:
        # column-parallel biases shard with the projection's output dim
        layer_specs["q_proj"]["bias"] = P(None, TENSOR)
        layer_specs["k_proj"]["bias"] = P(None, TENSOR)
        layer_specs["v_proj"]["bias"] = P(None, TENSOR)
    if cfg.num_experts > 1:
        # experts sharded over the "expert" mesh axis, TP within each expert
        layer_specs["router"] = {"kernel": P(None, None, None)}
        layer_specs["gate_proj"] = {"kernel": P(None, EXPERT, None, TENSOR)}
        layer_specs["up_proj"] = {"kernel": P(None, EXPERT, None, TENSOR)}
        layer_specs["down_proj"] = {"kernel": P(None, EXPERT, TENSOR, None)}
    else:
        layer_specs["gate_proj"] = {"kernel": P(None, None, TENSOR)}
        layer_specs["up_proj"] = {"kernel": P(None, None, TENSOR)}
        layer_specs["down_proj"] = {"kernel": P(None, TENSOR, None)}
    specs = {
        "embed": {"embedding": P(TENSOR, None)},
        "layers": layer_specs,
        "norm_f": {"scale": P(None)},
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"kernel": P(None, TENSOR)}
    return specs


# --------------------------------------------------------------------- #
# Building blocks
# --------------------------------------------------------------------- #
def rms_norm(x, scale, eps):
    # the fused path (kernels/fused_collective_matmul.rmsnorm_matmul)
    # folds exactly this composition into the consuming projection's
    # kernel — any change here must land there too (parity test-asserted)
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale


def _fused_rmsnorm_active(cfg: "TransformerConfig") -> bool:
    """"on"/"off" force; "auto" enables on TPU Pallas only — the CPU sim's
    jaxpr (and therefore every tier-1 numeric) is unchanged by default."""
    mode = getattr(cfg, "fused_rmsnorm", "auto")
    if mode in ("on", True):
        return True
    if mode in ("off", False):
        return False
    from ..kernels.fused_collective_matmul import supports_fused_rmsnorm

    return supports_fused_rmsnorm()


def rope_tables(seq_len: int, head_dim: int, theta: float, offset=0):
    pos = jnp.arange(seq_len, dtype=jnp.float32) + offset
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = jnp.outer(pos, inv)                      # [S, hd/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin):
    """x: [B, S, H, hd]; rotate pairs (even, odd stacked halves)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = cos[None, :, None, :].astype(x.dtype)
    sin = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _xla_attention(q, k, v, causal=True, seq_offset=0):
    """Plain XLA attention [B,S,H,hd] — fallback + CPU-sim path."""
    B, S, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    if causal:
        q_pos = jnp.arange(S)[:, None] + seq_offset
        k_pos = jnp.arange(k.shape[1])[None, :]
        mask = q_pos >= k_pos
        scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention(q, k, v, cfg: TransformerConfig, causal=True):
    """Dispatch to the Pallas flash kernel on TPU, XLA math elsewhere."""
    impl = cfg.attn_impl
    if impl == "auto":
        from ..accelerator import get_accelerator

        impl = "flash" if (cfg.use_flash and get_accelerator().supports_pallas()
                           and q.shape[1] >= 128) else "xla"
    if impl == "flash":
        from ..ops.transformer.flash_attention import flash_attention

        # the kernel clamps blocks to the (128-aligned) sequence itself —
        # pre-clamping here would feed it non-lane-aligned tiles
        return flash_attention(q, k, v, causal=causal,
                               block_q=cfg.flash_block_q,
                               block_k=cfg.flash_block_k)
    return _xla_attention(q, k, v, causal=causal)


# --------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------- #
def _activation_spec():
    return P((DATA_OUTER, DATA, EXPERT), SEQ, None)


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x  # outside jit/mesh context


def forward(params: Dict, tokens: jax.Array, cfg: TransformerConfig,
            dropout_rng: Optional[jax.Array] = None,
            return_aux_loss: bool = False) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, V] (+ MoE aux loss if requested)."""
    dtype = params["layers"]["q_proj"]["kernel"].dtype
    # jax.named_scope annotations flow into jaxpr name stacks (and xprof op
    # names), feeding the profiler's per-module cost tree
    # (profiling/module_tree.py) — zero runtime cost.
    with jax.named_scope("embed"):
        x = jnp.take(params["embed"]["embedding"], tokens, axis=0)
        x = _constrain(x, _activation_spec())
    S = tokens.shape[1]
    cos, sin = rope_tables(S, cfg.head_dim, cfg.rope_theta)

    def mlp_block(h, lp, fused_scale=None):
        if cfg.num_experts > 1:
            # Mixtral-style routed expert MLP (see moe/).  Default dispatch
            # is the sparse scatter/gather path (linear in routing-chunk
            # tokens); "dense" keeps the GShard [S,E,C] einsum as the oracle.
            from ..moe.sharded_moe import moe_mlp_block

            B_, S_, D_ = h.shape
            out, l_aux = moe_mlp_block(
                lp, h.reshape(-1, D_), k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                dispatch_impl=cfg.moe_dispatch)
            return out.reshape(B_, S_, D_), l_aux
        if fused_scale is not None:
            # fused path: h is the UN-normalized residual; the norm is
            # folded into the gate/up projection kernels (down has no
            # norm in front and stays a plain matmul)
            from ..kernels.fused_collective_matmul import rmsnorm_matmul

            gate = jax.nn.silu(rmsnorm_matmul(
                h, fused_scale, lp["gate_proj"]["kernel"], cfg.norm_eps))
            up = rmsnorm_matmul(h, fused_scale, lp["up_proj"]["kernel"],
                                cfg.norm_eps)
        else:
            gate = jax.nn.silu(h @ lp["gate_proj"]["kernel"])
            up = h @ lp["up_proj"]["kernel"]
        return (gate * up) @ lp["down_proj"]["kernel"], jnp.zeros((), jnp.float32)

    def proj(h, p, B, n_heads):
        y = h @ p["kernel"]
        if "bias" in p:
            y = y + p["bias"]
        return y.reshape(B, S, n_heads, cfg.head_dim)

    fused_norm = _fused_rmsnorm_active(cfg)

    def norm_proj(x, norm_scale, p, B, n_heads):
        """rms_norm folded into the projection kernel (the fused path's
        per-tile recompute of the norm is free VPU work; the normalized
        activations never hit HBM)."""
        from ..kernels.fused_collective_matmul import rmsnorm_matmul

        y = rmsnorm_matmul(x, norm_scale, p["kernel"], cfg.norm_eps)
        if "bias" in p:
            y = y + p["bias"]
        return y.reshape(B, S, n_heads, cfg.head_dim)

    def layer(carry, lp):
        from jax.ad_checkpoint import checkpoint_name

        x, aux = carry
        B = x.shape[0]
        with jax.named_scope("attention"):
            if fused_norm:
                ns = lp["attn_norm"]["scale"]
                q = norm_proj(x, ns, lp["q_proj"], B, cfg.num_heads)
                k = norm_proj(x, ns, lp["k_proj"], B, cfg.num_kv_heads)
                v = norm_proj(x, ns, lp["v_proj"], B, cfg.num_kv_heads)
            else:
                h = rms_norm(x, lp["attn_norm"]["scale"], cfg.norm_eps)
                q = proj(h, lp["q_proj"], B, cfg.num_heads)
                k = proj(h, lp["k_proj"], B, cfg.num_kv_heads)
                v = proj(h, lp["v_proj"], B, cfg.num_kv_heads)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            o = attention(q, k, v, cfg, causal=True)
            x = x + (o.reshape(B, S, -1) @ lp["o_proj"]["kernel"])
        # Named + mesh-sharded residual stream: the activation-checkpointing
        # config's save/offload policies select these by name (runtime/
        # activation_checkpointing/checkpointing.py RESIDUAL_NAMES), and the
        # sharding constraint means a saved residual is PARTITIONED over the
        # data/seq axes — the reference's partition_activations.
        x = checkpoint_name(_constrain(x, _activation_spec()), "attn_residual")
        with jax.named_scope("mlp"):
            if fused_norm and cfg.num_experts == 1:
                # norm folded into the gate/up kernels; MoE keeps the
                # unfused norm (the router needs h itself)
                mlp_out, l_aux = mlp_block(
                    x, lp, fused_scale=lp["mlp_norm"]["scale"])
            else:
                h = rms_norm(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
                mlp_out, l_aux = mlp_block(h, lp)
            x = x + mlp_out
        x = checkpoint_name(_constrain(x, _activation_spec()), "mlp_residual")
        return (x, aux + l_aux), None

    layer_fn = layer
    if cfg.remat:
        from ..runtime.activation_checkpointing import checkpointing as ac

        if ac.active():
            # DS-config activation_checkpointing (partition_activations /
            # cpu_checkpointing) overrides the model's own remat policy —
            # the config toggle must change execution (VERDICT r3 #5/#6)
            policy = ac.get_policy()
        else:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            if not callable(policy):
                valid = [n for n in dir(jax.checkpoint_policies)
                         if not n.startswith("_")]
                raise ValueError(
                    f"remat_policy={cfg.remat_policy!r} is not a "
                    f"jax.checkpoint_policies member; valid: {valid}")
        layer_fn = jax.checkpoint(layer, policy=policy)

    with jax.named_scope("layers"):
        (x, aux_loss), _ = jax.lax.scan(layer_fn,
                                        (x, jnp.zeros((), jnp.float32)),
                                        params["layers"])
    with jax.named_scope("final_norm"):
        x = rms_norm(x, params["norm_f"]["scale"], cfg.norm_eps)
    with jax.named_scope("lm_head"):
        if cfg.tie_embeddings:
            logits = x @ params["embed"]["embedding"].T
        else:
            logits = x @ params["lm_head"]["kernel"]
    if return_aux_loss:
        return logits, aux_loss
    return logits


def lm_loss(params: Dict, batch: Any, cfg: TransformerConfig,
            rng: Optional[jax.Array] = None) -> jax.Array:
    """Causal LM loss: predict batch['input_ids'] shifted by one.

    Accepts {'input_ids': [B,S]} (+ optional 'labels' [B,S] with -100 ignore).
    """
    tokens = batch["input_ids"] if isinstance(batch, dict) else batch
    labels = batch.get("labels") if isinstance(batch, dict) else None
    logits, aux_loss = forward(params, tokens, cfg, return_aux_loss=True)
    if labels is None:
        labels = jnp.pad(tokens[:, 1:], ((0, 0), (0, 1)), constant_values=-100)
    with jax.named_scope("loss"):
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = labels >= 0
        safe_labels = jnp.where(valid, labels, 0)
        token_logp = jnp.take_along_axis(
            logp, safe_labels[..., None], axis=-1)[..., 0]
        loss = -jnp.sum(token_logp * valid) / jnp.maximum(jnp.sum(valid), 1)
    if cfg.num_experts > 1:
        loss = loss + cfg.moe_aux_loss_coef * aux_loss / cfg.num_layers
    return loss


class CausalLM:
    """Model object consumable by ``deepspeed_tpu.initialize``.

    Exposes ``loss_fn(params, batch, rng)``, ``partition_specs`` (read by the
    engine's ZeRO plan as TP base specs), and ``init_params``.
    """

    def __init__(self, cfg: TransformerConfig):
        self.config = cfg
        self.partition_specs = partition_specs(cfg)

    def init_params(self, key: jax.Array, dtype=jnp.float32):
        return init_params(self.config, key, dtype)

    def loss_fn(self, params, batch, rng):
        return lm_loss(params, batch, self.config, rng)

    def __call__(self, params, tokens):
        return forward(params, tokens, self.config)

    def num_params(self, params=None) -> int:
        if params is None:
            params = jax.eval_shape(lambda k: self.init_params(k), jax.random.PRNGKey(0))
        import numpy as np

        return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))

    def flops_per_token(self) -> float:
        """~6N flops/token for training (fwd+bwd), N = non-embedding params."""
        cfg = self.config
        D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
        per_layer = 2 * D * (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim \
            + 2 * cfg.num_heads * cfg.head_dim * D + 3 * 2 * D * F
        return 3 * (L * per_layer + 2 * D * cfg.vocab_size)
