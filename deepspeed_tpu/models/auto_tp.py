"""AutoTP — automatic tensor-parallel spec inference for arbitrary models
(reference: module_inject/auto_tp.py:192 ``AutoTP`` + tp_shard.py helpers).

The reference walks the torch module graph classifying each Linear as
row/column-parallel and patching it with LinearAllreduce/LinearLayer.  The
TPU equivalent classifies each weight LEAF of a param pytree and emits a
``PartitionSpec`` tree — GSPMD then inserts the allreduces the reference
writes by hand.  Classification mirrors the reference's policy:

  * column-parallel (shard the OUTPUT dim): q/k/v/query/key/value, gate/up,
    fc1/c_fc/dense_h_to_4h, w1/w3 — producers whose outputs stay sharded
    until the row-parallel consumer.
  * row-parallel (shard the INPUT dim): o_proj/out_proj/dense/c_proj,
    down/fc2/dense_4h_to_h, w2 — a psum follows (GSPMD inserts it).
  * everything else (norms, biases of row-parallel layers, embeddings by
    default): replicated.

A weight only shards when the target dim divides ``tp_size`` — the
reference's tp_shard divisibility checks — otherwise it stays replicated
with a warning.
"""
from __future__ import annotations

import re
from typing import Any, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from ..runtime.topology import TENSOR
from ..utils.logging import logger

#: name-pattern policy (reference auto_tp.py's layer-name policies)
COLUMN_PATTERNS = (
    r"q_proj", r"k_proj", r"v_proj", r"\bquery\b", r"\bkey\b", r"\bvalue\b",
    r"query_key_value", r"gate_proj", r"up_proj", r"\bfc1\b", r"c_fc",
    r"dense_h_to_4h", r"\bw1\b", r"\bw3\b", r"wi\b",
)
ROW_PATTERNS = (
    r"o_proj", r"out_proj", r"down_proj", r"\bfc2\b", r"c_proj",
    r"dense_4h_to_h", r"\bw2\b", r"wo\b", r"attn[._]dense", r"attention[._]dense",
)


def _classify(path: str) -> Optional[str]:
    for pat in ROW_PATTERNS:
        if re.search(pat, path):
            return "row"
    for pat in COLUMN_PATTERNS:
        if re.search(pat, path):
            return "column"
    return None


def _path_str(path) -> str:
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def autotp_specs(params: Any, tp_size: int,
                 stacked_leading_dims: int = 1) -> Any:
    """Infer a TP ``PartitionSpec`` tree for an arbitrary param pytree.

    ``stacked_leading_dims``: number of leading stacked-layer dims under
    "layers." (1 for this repo's [L, ...] arrays — the default, matching
    :func:`autotp_shard`) that must never be sharded by TP; pass 0 for
    flat per-layer trees.
    """
    def leaf_spec(path, x):
        ndim = getattr(x, "ndim", 0)
        if ndim < 2 or tp_size <= 1:
            return P()
        pstr = _path_str(path)
        kind = _classify(pstr)
        if kind is None:
            return P(*([None] * ndim))
        lead = stacked_leading_dims if pstr.startswith("layers") else 0
        # weights [.., in, out]: column shards -1, row shards -2;
        # 1D-bias-like leaves (after stacking) follow the output dim
        dim = ndim - 1 if kind == "column" else ndim - 2
        if dim < lead:
            return P(*([None] * ndim))
        if kind == "row" and ndim - lead == 1:
            return P(*([None] * ndim))   # row-parallel bias: replicated
        if x.shape[dim] % tp_size != 0:
            logger.warning(f"AutoTP: {pstr} dim {dim} size {x.shape[dim]} "
                           f"not divisible by tp={tp_size}; replicating")
            return P(*([None] * ndim))
        entries = [None] * ndim
        entries[dim] = TENSOR
        return P(*entries)

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def autotp_shard(params: Any, tp_size: int, mesh=None,
                 stacked_leading_dims: int = 1) -> Tuple[Any, Any]:
    """Classify + place: returns (sharded params, spec tree).  The runtime
    analogue of reference ``AutoTP.replace_module`` + tp_shard."""
    from jax.sharding import NamedSharding

    from ..runtime.topology import get_topology

    mesh = mesh or get_topology().mesh
    specs = autotp_specs(params, tp_size, stacked_leading_dims)
    placed = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: hasattr(x, "ndim"))
    return placed, specs
