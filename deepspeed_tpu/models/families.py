"""Per-architecture HF model families beyond the Llama recipe.

Reference analogues: ``module_inject/containers/`` (gpt2/opt/bloom/falcon
per-arch policies) and ``inference/v2/model_implementations/`` (falcon, phi,
qwen, opt per-arch model classes).  Round 1 ran these families on the Llama
compute path with a warning; this module implements the EXACT architectures —
LayerNorm with bias, learned/ALiBi positions, fused-QKV layouts, parallel
attention blocks, partial rotary — verified by logit-parity tests against HF
transformers (tests/unit/test_hf_parity.py).

One generalized transformer (:class:`UniversalCausalLM`) is driven by
:class:`ArchConfig` knobs rather than one class per architecture — on TPU the
differences are pure math selection, and a single stacked-layer scan keeps
XLA compilation shared across families.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import rms_norm


@dataclasses.dataclass
class ArchConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 12
    max_seq_len: int = 1024
    #: "rope" | "learned" | "alibi"
    pos: str = "learned"
    pos_offset: int = 0             # OPT stores positions at index pos+2
    rope_theta: float = 10000.0
    rope_pct: float = 1.0           # phi: rotary on a fraction of head_dim
    #: "neox" (half-split halves, llama/falcon/phi) | "gptj" (interleaved
    #: pairs, rotate_every_two)
    rope_style: str = "neox"
    #: "layernorm" | "rmsnorm"
    norm: str = "layernorm"
    norm_eps: float = 1e-5
    #: "gelu" | "relu" | "silu_glu"
    mlp: str = "gelu"
    gelu_exact: bool = False        # falcon uses erf-gelu; gpt2/bloom/phi tanh
    parallel_attn: bool = False     # falcon/phi: attn + mlp from the same input
    #: falcon-style ALiBi: bias added before 1/sqrt(hd) scaling, slope*pos in
    #: bf16 (bloom adds the unscaled f32 bias after scaling)
    alibi_scaled: bool = False
    dual_ln: bool = False           # falcon new-arch: separate ln_attn/ln_mlp
    qkv_bias: bool = True
    out_bias: bool = True           # o_proj bias
    mlp_bias: Optional[bool] = None  # fc biases (None → follow out_bias)
    embed_layernorm: bool = False   # bloom
    tie_embeddings: bool = True
    lm_head_bias: bool = False      # gptj/phi carry an lm-head bias

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        rd = int(self.head_dim * self.rope_pct)
        return rd - rd % 2


# --------------------------------------------------------------------- #
# Math blocks
# --------------------------------------------------------------------- #
def layer_norm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


def _norm(x, p, cfg: ArchConfig):
    if cfg.norm == "rmsnorm":
        return rms_norm(x, p["scale"], cfg.norm_eps)
    return layer_norm(x, p["scale"], p["bias"], cfg.norm_eps)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Standard ALiBi slopes (bloom/modeling_bloom.py build_alibi_tensor)."""
    closest = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** (i + 1) for i in range(closest)]
    if closest != num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        extra = [extra_base ** (2 * i + 1)
                 for i in range(num_heads - closest)]
        slopes += extra
    return np.asarray(slopes, np.float32)


def _rope_partial(x, cos, sin, rotary_dim, style="neox"):
    """Rope on the first ``rotary_dim`` features of each head.

    "neox": rotate split halves (llama/falcon/phi).  "gptj": rotate
    interleaved even/odd pairs (rotate_every_two)."""
    rot, passthrough = x[..., :rotary_dim], x[..., rotary_dim:]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    if style == "gptj":
        x1, x2 = rot[..., 0::2], rot[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x1 * s + x2 * c
        rot = jnp.stack([r1, r2], axis=-1).reshape(rot.shape)
    else:
        x1, x2 = jnp.split(rot, 2, axis=-1)
        rot = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return jnp.concatenate([rot, passthrough], axis=-1) \
        if rotary_dim < x.shape[-1] else rot


def _attention(q, k, v, cfg: ArchConfig, alibi: Optional[jnp.ndarray]):
    B, S, H, hd = q.shape
    if alibi is None and S >= 128 and jax.default_backend() == "tpu":
        # non-alibi families ride the Pallas flash kernel; the O(S²) f32
        # score materialization below is the CPU/short-seq fallback only
        from ..ops.transformer.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(hd)
    if alibi is not None:
        # ALiBi (bloom build_alibi_tensor): slope_h * k_pos — equivalent to
        # slope*(k_pos - q_pos) under softmax's per-row shift invariance.
        if cfg.alibi_scaled:
            # falcon variant (modeling_falcon.py:397-398): the bias is added
            # BEFORE the 1/sqrt(hd) scaling and slope*pos is computed in bf16
            bias = (alibi.astype(jnp.bfloat16)[None, :, None, None] *
                    jnp.arange(S, dtype=jnp.bfloat16)[None, None, None, :]
                    ).astype(jnp.float32) / math.sqrt(hd)
        else:
            bias = alibi[None, :, None, None] * \
                jnp.arange(S, dtype=jnp.float32)[None, None, None, :]
        scores = scores + bias
    mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _proj(x, p):
    y = x @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    return y


# --------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------- #
def universal_forward(params: Dict, tokens: jnp.ndarray,
                      cfg: ArchConfig) -> jnp.ndarray:
    B, S = tokens.shape
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    x = jnp.take(params["embed"]["embedding"], tokens, axis=0)
    if cfg.pos == "learned":
        pos = jnp.arange(S) + cfg.pos_offset
        x = x + jnp.take(params["pos_embed"]["embedding"], pos, axis=0)
    if cfg.embed_layernorm:
        x = _norm(x, params["embed_ln"], cfg)

    cos = sin = None
    if cfg.pos == "rope":
        rd = cfg.rotary_dim
        inv = 1.0 / (cfg.rope_theta **
                     (jnp.arange(0, rd, 2, dtype=jnp.float32) / rd))
        freqs = jnp.outer(jnp.arange(S, dtype=jnp.float32), inv)
        cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    alibi = jnp.asarray(alibi_slopes(H)) if cfg.pos == "alibi" else None

    def layer(x, lp):
        h_attn_in = _norm(x, lp["ln1"], cfg)
        q = _proj(h_attn_in, lp["q_proj"]).reshape(B, S, H, hd)
        k = _proj(h_attn_in, lp["k_proj"]).reshape(B, S, KV, hd)
        v = _proj(h_attn_in, lp["v_proj"]).reshape(B, S, KV, hd)
        if cfg.pos == "rope":
            q = _rope_partial(q, cos, sin, cfg.rotary_dim, cfg.rope_style)
            k = _rope_partial(k, cos, sin, cfg.rotary_dim, cfg.rope_style)
        o = _attention(q, k, v, cfg, alibi).reshape(B, S, H * hd)
        attn_out = _proj(o, lp["o_proj"])

        if cfg.parallel_attn:
            h_mlp_in = _norm(x, lp["ln2"], cfg) if cfg.dual_ln else h_attn_in
        else:
            x = x + attn_out
            h_mlp_in = _norm(x, lp["ln2"], cfg)

        if cfg.mlp == "silu_glu":
            gate = jax.nn.silu(_proj(h_mlp_in, lp["gate_proj"]))
            up = _proj(h_mlp_in, lp["up_proj"])
            mlp_out = _proj(gate * up, lp["down_proj"])
        else:
            if cfg.mlp == "gelu":
                act = lambda x: jax.nn.gelu(x, approximate=not cfg.gelu_exact)
            else:
                act = jax.nn.relu
            mlp_out = _proj(act(_proj(h_mlp_in, lp["fc1"])), lp["fc2"])

        if cfg.parallel_attn:
            x = x + attn_out + mlp_out
        else:
            x = x + mlp_out
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = _norm(x, params["norm_f"], cfg)
    if cfg.tie_embeddings:
        return x @ params["embed"]["embedding"].T
    logits = x @ params["lm_head"]["kernel"]
    if "bias" in params["lm_head"]:                 # phi has an lm-head bias
        logits = logits + params["lm_head"]["bias"]
    return logits


def init_universal_params(cfg: ArchConfig, key: jax.Array,
                          dtype=jnp.float32) -> Dict:
    """Random init matching the per-arch converters' parameter layout."""
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    ks = iter(jax.random.split(key, 16))

    def dense(shape, fan_in, bias_dim=None):
        p = {"kernel": (jax.random.normal(next(ks), shape) /
                        math.sqrt(fan_in)).astype(dtype)}
        if bias_dim is not None:
            p["bias"] = jnp.zeros(bias_dim, dtype)
        return p

    def ln():
        p = {"scale": jnp.ones((L, D), dtype)}
        if cfg.norm == "layernorm":
            p["bias"] = jnp.zeros((L, D), dtype)
        return p

    qb = (L, H * hd) if cfg.qkv_bias else None
    kvb = (L, KV * hd) if cfg.qkv_bias else None
    ob = (L, D) if cfg.out_bias else None
    layers = {
        "ln1": ln(),
        "q_proj": dense((L, D, H * hd), D, qb),
        "k_proj": dense((L, D, KV * hd), D, kvb),
        "v_proj": dense((L, D, KV * hd), D, kvb),
        "o_proj": dense((L, H * hd, D), H * hd, ob),
    }
    if not (cfg.parallel_attn and not cfg.dual_ln):
        layers["ln2"] = ln()
    mlp_bias = cfg.out_bias if cfg.mlp_bias is None else cfg.mlp_bias
    if cfg.mlp == "silu_glu":
        layers["gate_proj"] = dense((L, D, F), D)
        layers["up_proj"] = dense((L, D, F), D)
        layers["down_proj"] = dense((L, F, D), F)
    else:
        layers["fc1"] = dense((L, D, F), D, (L, F) if mlp_bias else None)
        layers["fc2"] = dense((L, F, D), F, (L, D) if mlp_bias else None)

    params = {
        "embed": {"embedding": (jax.random.normal(next(ks),
                                                  (cfg.vocab_size, D)) * 0.02
                                ).astype(dtype)},
        "layers": layers,
        "norm_f": {"scale": jnp.ones((D,), dtype)},
    }
    if cfg.norm == "layernorm":
        params["norm_f"]["bias"] = jnp.zeros((D,), dtype)
    if cfg.pos == "learned":
        params["pos_embed"] = {"embedding": (jax.random.normal(
            next(ks), (cfg.max_seq_len + cfg.pos_offset, D)) * 0.02
        ).astype(dtype)}
    if cfg.embed_layernorm:
        params["embed_ln"] = {"scale": jnp.ones((D,), dtype),
                              "bias": jnp.zeros((D,), dtype)}
    if not cfg.tie_embeddings:
        params["lm_head"] = dense((D, cfg.vocab_size), D,
                                  (cfg.vocab_size,) if cfg.lm_head_bias
                                  else None)
    return params


class UniversalCausalLM:
    """Per-arch compat model with the same engine interface as CausalLM."""

    def __init__(self, cfg: ArchConfig):
        self.config = cfg
        self.partition_specs = None   # replicated; TP comes from AutoTP specs

    def init_params(self, key: jax.Array, dtype=jnp.float32):
        return init_universal_params(self.config, key, dtype)

    def __call__(self, params, tokens):
        return universal_forward(params, tokens, self.config)

    def loss_fn(self, params, batch, rng=None):
        tokens = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = universal_forward(params, tokens, self.config)
        labels = jnp.pad(tokens[:, 1:], ((0, 0), (0, 1)), constant_values=-100)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        tl = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return -jnp.sum(tl * valid) / jnp.maximum(jnp.sum(valid), 1)

    def num_params(self, params) -> int:
        return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))
