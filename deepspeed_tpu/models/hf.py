"""HuggingFace model-family support.

Reference analogues: ``module_inject/replace_policy.py`` + ``containers/``
(BERT/GPT/LLaMA/OPT/BLOOM/Falcon/Qwen/Mistral/Mixtral policies) and the v2
``model_implementations`` per-arch directories, plus AutoTP
(``module_inject/auto_tp.py:192``) and ``tp_model_init``
(deepspeed/__init__.py:369).

TPU design: instead of monkey-patching torch modules, each supported HF
architecture maps to a :class:`TransformerConfig` ("policy") and a weight
converter that reads an HF torch ``state_dict`` (CPU torch is in the image)
into this framework's parameter pytree.  TP then falls out of
``partition_specs`` — the AutoTP row/col analysis is already encoded there.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..utils.logging import logger
from .transformer import CausalLM, TransformerConfig

#: HF architecture name → config-field mapping ("policy map")
_ARCH_POLICIES = {
    "LlamaForCausalLM": "llama",
    "MistralForCausalLM": "llama",
    "Qwen2ForCausalLM": "qwen2",      # llama + qkv bias, native CausalLM path
    "GPT2LMHeadModel": "gpt2",
    "GPTJForCausalLM": "gptj",
    "OPTForCausalLM": "opt",
    "BloomForCausalLM": "bloom",
    "FalconForCausalLM": "falcon",
    "PhiForCausalLM": "phi",
    "MixtralForCausalLM": "mixtral",
}

#: families on the TP/MoE/flash/paged-serving-native CausalLM path
NATIVE_FAMILIES = ("llama", "qwen2", "mixtral")


def policy_for(hf_config: Any) -> str:
    archs = getattr(hf_config, "architectures", None) or []
    for a in archs:
        if a in _ARCH_POLICIES:
            return _ARCH_POLICIES[a]
    mt = getattr(hf_config, "model_type", "")
    for name, fam in (("llama", "llama"), ("mistral", "llama"),
                      ("qwen2", "qwen2"), ("gpt2", "gpt2"), ("opt", "opt"),
                      ("bloom", "bloom"), ("falcon", "falcon"), ("phi", "phi"),
                      ("gptj", "gptj"), ("mixtral", "mixtral")):
        if mt == name:
            return fam
    raise ValueError(f"unsupported HF architecture: {archs or mt}")


def _hf_get(hf_config, *names, default=None):
    return next((getattr(hf_config, n) for n in names
                 if getattr(hf_config, n, None) is not None), default)


def config_from_hf(hf_config: Any, **overrides) -> TransformerConfig:
    """HF config → TransformerConfig (the per-arch 'container' policy) for
    the llama/mixtral (RoPE+RMSNorm) families.  Other families get exact
    per-arch recipes via :func:`arch_config_from_hf` (models/families.py)."""
    fam = policy_for(hf_config)
    g = lambda *names, default=None: _hf_get(hf_config, *names, default=default)
    hidden = g("hidden_size", "n_embd", default=768)
    heads = g("num_attention_heads", "n_head", default=12)
    kw = dict(
        vocab_size=g("vocab_size", default=32000),
        hidden_size=hidden,
        intermediate_size=g("intermediate_size", "n_inner", default=4 * hidden),
        num_layers=g("num_hidden_layers", "n_layer", default=12),
        num_heads=heads,
        num_kv_heads=g("num_key_value_heads", default=heads),
        max_seq_len=g("max_position_embeddings", "n_positions", default=2048),
        rope_theta=g("rope_theta", default=10000.0),
        norm_eps=g("rms_norm_eps", "layer_norm_epsilon", default=1e-5),
        tie_embeddings=bool(g("tie_word_embeddings", default=False)),
    )
    if fam == "mixtral":
        kw.update(num_experts=g("num_local_experts", default=8),
                  moe_top_k=g("num_experts_per_tok", default=2))
    if fam == "qwen2":
        kw.update(attn_bias=True)   # qwen2 = llama + q/k/v biases
    kw.update(overrides)
    return TransformerConfig(**kw)


def arch_config_from_hf(hf_config: Any, **overrides):
    """HF config → exact :class:`ArchConfig` for the non-llama families."""
    from .families import ArchConfig

    fam = policy_for(hf_config)
    g = lambda *names, default=None: _hf_get(hf_config, *names, default=default)
    hidden = g("hidden_size", "n_embd", default=768)
    heads = g("num_attention_heads", "n_head", default=12)
    base = dict(
        vocab_size=g("vocab_size", default=50257),
        hidden_size=hidden,
        intermediate_size=g("intermediate_size", "n_inner",
                            "ffn_hidden_size", default=4 * hidden),
        num_layers=g("num_hidden_layers", "n_layer", default=12),
        num_heads=heads,
        num_kv_heads=heads,
        max_seq_len=g("max_position_embeddings", "n_positions", default=2048),
        norm_eps=g("layer_norm_epsilon", "layer_norm_eps", "rms_norm_eps",
                   default=1e-5),
        tie_embeddings=bool(g("tie_word_embeddings", default=True)),
    )
    if fam == "gpt2":
        base.update(pos="learned", norm="layernorm", mlp="gelu",
                    qkv_bias=True, out_bias=True)
    elif fam == "opt":
        proj_dim = g("word_embed_proj_dim", default=hidden)
        if proj_dim != hidden:
            raise ValueError(
                f"OPT word_embed_proj_dim={proj_dim} != hidden_size={hidden} "
                f"(opt-350m's project_in/out) is not supported yet")
        if not getattr(hf_config, "do_layer_norm_before", True):
            raise ValueError("OPT do_layer_norm_before=False (opt-350m "
                             "post-LN variant) is not supported yet")
        base.update(pos="learned", pos_offset=2, norm="layernorm", mlp="relu",
                    qkv_bias=True, out_bias=True,
                    intermediate_size=g("ffn_dim", default=4 * hidden))
    elif fam == "bloom":
        base.update(pos="alibi", norm="layernorm", mlp="gelu",
                    embed_layernorm=True, qkv_bias=True, out_bias=True,
                    intermediate_size=4 * hidden)
    elif fam == "falcon":
        new_arch = bool(g("new_decoder_architecture", default=False))
        kv = g("num_kv_heads", default=None) if new_arch else \
            (1 if g("multi_query", default=True) else heads)
        # falcon-rw checkpoints (modeling_falcon.py FalconConfig): alibi=True
        # replaces rotary; parallel_attn=False is the sequential residual
        # (needs ln2 from post_attention_layernorm — see the converter)
        base.update(pos="alibi" if g("alibi", default=False) else "rope",
                    alibi_scaled=bool(g("alibi", default=False)),
                    norm="layernorm", mlp="gelu", gelu_exact=True,
                    parallel_attn=bool(g("parallel_attn", default=True)),
                    dual_ln=new_arch, num_kv_heads=kv or heads,
                    qkv_bias=bool(g("bias", default=False)),
                    out_bias=bool(g("bias", default=False)),
                    rope_theta=g("rope_theta", default=10000.0),
                    intermediate_size=4 * hidden)
    elif fam == "gptj":
        base.update(pos="rope", rope_style="gptj", norm="layernorm",
                    mlp="gelu", parallel_attn=True, dual_ln=False,
                    qkv_bias=False, out_bias=False, mlp_bias=True,
                    rope_pct=(g("rotary_dim", default=hidden // heads) /
                              (hidden // heads)),
                    tie_embeddings=False, lm_head_bias=True)
    elif fam == "phi":
        base.update(pos="rope", norm="layernorm", mlp="gelu",
                    parallel_attn=True, dual_ln=False,
                    qkv_bias=True, out_bias=True,
                    rope_pct=float(g("partial_rotary_factor", default=0.5)),
                    rope_theta=g("rope_theta", default=10000.0),
                    num_kv_heads=g("num_key_value_heads", default=heads),
                    tie_embeddings=False, lm_head_bias=True)
    else:
        raise ValueError(f"no exact ArchConfig recipe for family {fam!r}")
    base.update(overrides)
    return ArchConfig(**base)


def from_pretrained_config(name_or_config: Any, **overrides):
    """Build a model from an HF config object or model-name string.

    llama/mistral/mixtral map onto the TP/MoE-native :class:`CausalLM`;
    other families get exact per-arch :class:`UniversalCausalLM` recipes."""
    cfg = name_or_config
    if isinstance(name_or_config, str):
        from transformers import AutoConfig

        cfg = AutoConfig.from_pretrained(name_or_config)
    fam = policy_for(cfg)
    if fam in NATIVE_FAMILIES:
        return CausalLM(config_from_hf(cfg, **overrides))
    from .families import UniversalCausalLM

    return UniversalCausalLM(arch_config_from_hf(cfg, **overrides))


# --------------------------------------------------------------------- #
# Weight conversion (HF torch state_dict → framework pytree)
# --------------------------------------------------------------------- #
def convert_llama_state_dict(sd: Dict[str, Any], cfg: TransformerConfig) -> Dict:
    """Llama/Mistral/Qwen2 HF checkpoint → stacked-layer pytree."""
    import jax.numpy as jnp

    def t(name):
        w = sd[name]
        if hasattr(w, "numpy"):
            w = w.float().numpy()
        return np.asarray(w, np.float32)

    L = cfg.num_layers

    def stack(fmt, transpose=True):
        ws = [t(fmt.format(i)) for i in range(L)]
        arr = np.stack([w.T if transpose else w for w in ws])
        return jnp.asarray(arr)

    params = {
        "embed": {"embedding": jnp.asarray(t("model.embed_tokens.weight"))},
        "layers": {
            "attn_norm": {"scale": stack("model.layers.{}.input_layernorm.weight",
                                         transpose=False)},
            "q_proj": {"kernel": stack("model.layers.{}.self_attn.q_proj.weight")},
            "k_proj": {"kernel": stack("model.layers.{}.self_attn.k_proj.weight")},
            "v_proj": {"kernel": stack("model.layers.{}.self_attn.v_proj.weight")},
            "o_proj": {"kernel": stack("model.layers.{}.self_attn.o_proj.weight")},
            "mlp_norm": {"scale": stack("model.layers.{}.post_attention_layernorm.weight",
                                        transpose=False)},
        },
        "norm_f": {"scale": jnp.asarray(t("model.norm.weight"))},
    }
    if cfg.attn_bias:
        for proj in ("q_proj", "k_proj", "v_proj"):
            params["layers"][proj]["bias"] = stack(
                "model.layers.{}.self_attn." + proj + ".bias", transpose=False)
    if cfg.num_experts > 1:
        # Mixtral expert import (reference: model_implementations/mixtral):
        # w1=gate, w3=up, w2=down per expert; router = block_sparse_moe.gate.
        E = cfg.num_experts
        moe = "model.layers.{}.block_sparse_moe"

        def stack_experts(w_name):
            return jnp.asarray(np.stack([
                np.stack([t(f"{moe.format(i)}.experts.{e}.{w_name}.weight").T
                          for e in range(E)]) for i in range(L)]))

        params["layers"]["router"] = {
            "kernel": stack(moe + ".gate.weight")}
        params["layers"]["gate_proj"] = {"kernel": stack_experts("w1")}
        params["layers"]["up_proj"] = {"kernel": stack_experts("w3")}
        params["layers"]["down_proj"] = {"kernel": stack_experts("w2")}
    else:
        params["layers"]["gate_proj"] = {
            "kernel": stack("model.layers.{}.mlp.gate_proj.weight")}
        params["layers"]["up_proj"] = {
            "kernel": stack("model.layers.{}.mlp.up_proj.weight")}
        params["layers"]["down_proj"] = {
            "kernel": stack("model.layers.{}.mlp.down_proj.weight")}
    if not cfg.tie_embeddings and "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": jnp.asarray(t("lm_head.weight").T)}
    return params


# --------------------------------------------------------------------- #
# Exact per-arch conversions (UniversalCausalLM families)
# --------------------------------------------------------------------- #
def convert_arch_state_dict(sd: Dict[str, Any], cfg, fam: str) -> Dict:
    """gpt2/opt/bloom/falcon/phi/qwen2 HF checkpoint → UniversalCausalLM
    pytree (reference: module_inject/containers per-arch param mappings)."""
    import jax.numpy as jnp

    def t(name):
        w = sd[name]
        if hasattr(w, "numpy"):
            w = w.float().numpy()
        return np.asarray(w, np.float32)

    L, D, H, KV, hd = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                       cfg.num_kv_heads, cfg.head_dim)

    def stack(fmt, transpose=True):
        ws = [t(fmt.format(i)) for i in range(L)]
        return jnp.asarray(np.stack([w.T if transpose else w for w in ws]))

    def lin(fmt, bias_fmt=None, transpose=True):
        p = {"kernel": stack(fmt, transpose)}
        if bias_fmt is not None:
            p["bias"] = stack(bias_fmt, transpose=False)
        return p

    def ln(w_fmt, b_fmt):
        return {"scale": stack(w_fmt, transpose=False),
                "bias": stack(b_fmt, transpose=False)}

    if fam == "gpt2":
        # Conv1D weights are [in, out] — NOT transposed.  Fused c_attn
        # [D, 3D] splits along the output dim.
        qkv = np.stack([t(f"transformer.h.{i}.attn.c_attn.weight")
                        for i in range(L)])                     # [L, D, 3D]
        qkv_b = np.stack([t(f"transformer.h.{i}.attn.c_attn.bias")
                          for i in range(L)])                   # [L, 3D]
        q, k, v = np.split(qkv, 3, axis=2)
        qb, kb, vb = np.split(qkv_b, 3, axis=1)
        layers = {
            "ln1": ln("transformer.h.{}.ln_1.weight", "transformer.h.{}.ln_1.bias"),
            "ln2": ln("transformer.h.{}.ln_2.weight", "transformer.h.{}.ln_2.bias"),
            "q_proj": {"kernel": jnp.asarray(q), "bias": jnp.asarray(qb)},
            "k_proj": {"kernel": jnp.asarray(k), "bias": jnp.asarray(kb)},
            "v_proj": {"kernel": jnp.asarray(v), "bias": jnp.asarray(vb)},
            "o_proj": lin("transformer.h.{}.attn.c_proj.weight",
                          "transformer.h.{}.attn.c_proj.bias", transpose=False),
            "fc1": lin("transformer.h.{}.mlp.c_fc.weight",
                       "transformer.h.{}.mlp.c_fc.bias", transpose=False),
            "fc2": lin("transformer.h.{}.mlp.c_proj.weight",
                       "transformer.h.{}.mlp.c_proj.bias", transpose=False),
        }
        return {
            "embed": {"embedding": jnp.asarray(t("transformer.wte.weight"))},
            "pos_embed": {"embedding": jnp.asarray(t("transformer.wpe.weight"))},
            "layers": layers,
            "norm_f": {"scale": jnp.asarray(t("transformer.ln_f.weight")),
                       "bias": jnp.asarray(t("transformer.ln_f.bias"))},
        }

    if fam == "opt":
        p = "model.decoder.layers.{}"
        layers = {
            "ln1": ln(p + ".self_attn_layer_norm.weight",
                      p + ".self_attn_layer_norm.bias"),
            "ln2": ln(p + ".final_layer_norm.weight",
                      p + ".final_layer_norm.bias"),
            "q_proj": lin(p + ".self_attn.q_proj.weight", p + ".self_attn.q_proj.bias"),
            "k_proj": lin(p + ".self_attn.k_proj.weight", p + ".self_attn.k_proj.bias"),
            "v_proj": lin(p + ".self_attn.v_proj.weight", p + ".self_attn.v_proj.bias"),
            "o_proj": lin(p + ".self_attn.out_proj.weight", p + ".self_attn.out_proj.bias"),
            "fc1": lin(p + ".fc1.weight", p + ".fc1.bias"),
            "fc2": lin(p + ".fc2.weight", p + ".fc2.bias"),
        }
        return {
            "embed": {"embedding": jnp.asarray(t("model.decoder.embed_tokens.weight"))},
            "pos_embed": {"embedding": jnp.asarray(t("model.decoder.embed_positions.weight"))},
            "layers": layers,
            "norm_f": {"scale": jnp.asarray(t("model.decoder.final_layer_norm.weight")),
                       "bias": jnp.asarray(t("model.decoder.final_layer_norm.bias"))},
        }

    if fam == "bloom":
        p = "transformer.h.{}"
        # fused qkv rows are ordered [H, 3, hd] (modeling_bloom)
        qs, ks, vs, qbs, kbs, vbs = [], [], [], [], [], []
        for i in range(L):
            w = t(f"transformer.h.{i}.self_attention.query_key_value.weight")
            b = t(f"transformer.h.{i}.self_attention.query_key_value.bias")
            w = w.reshape(H, 3, hd, D)
            b = b.reshape(H, 3, hd)
            qs.append(w[:, 0].reshape(H * hd, D).T)
            ks.append(w[:, 1].reshape(H * hd, D).T)
            vs.append(w[:, 2].reshape(H * hd, D).T)
            qbs.append(b[:, 0].reshape(-1))
            kbs.append(b[:, 1].reshape(-1))
            vbs.append(b[:, 2].reshape(-1))
        layers = {
            "ln1": ln(p + ".input_layernorm.weight", p + ".input_layernorm.bias"),
            "ln2": ln(p + ".post_attention_layernorm.weight",
                      p + ".post_attention_layernorm.bias"),
            "q_proj": {"kernel": jnp.asarray(np.stack(qs)), "bias": jnp.asarray(np.stack(qbs))},
            "k_proj": {"kernel": jnp.asarray(np.stack(ks)), "bias": jnp.asarray(np.stack(kbs))},
            "v_proj": {"kernel": jnp.asarray(np.stack(vs)), "bias": jnp.asarray(np.stack(vbs))},
            "o_proj": lin(p + ".self_attention.dense.weight",
                          p + ".self_attention.dense.bias"),
            "fc1": lin(p + ".mlp.dense_h_to_4h.weight", p + ".mlp.dense_h_to_4h.bias"),
            "fc2": lin(p + ".mlp.dense_4h_to_h.weight", p + ".mlp.dense_4h_to_h.bias"),
        }
        return {
            "embed": {"embedding": jnp.asarray(t("transformer.word_embeddings.weight"))},
            "embed_ln": {"scale": jnp.asarray(t("transformer.word_embeddings_layernorm.weight")),
                         "bias": jnp.asarray(t("transformer.word_embeddings_layernorm.bias"))},
            "layers": layers,
            "norm_f": {"scale": jnp.asarray(t("transformer.ln_f.weight")),
                       "bias": jnp.asarray(t("transformer.ln_f.bias"))},
        }

    if fam == "falcon":
        p = "transformer.h.{}"
        G = H // KV                     # query heads per kv head
        qs, ks, vs = [], [], []
        for i in range(L):
            w = t(f"transformer.h.{i}.self_attention.query_key_value.weight")
            # rows ordered [KV, G+2, hd]: G query heads then k then v per group
            w = w.reshape(KV, G + 2, hd, D)
            qs.append(w[:, :G].reshape(KV * G * hd, D).T)
            ks.append(w[:, G].reshape(KV * hd, D).T)
            vs.append(w[:, G + 1].reshape(KV * hd, D).T)
        layers = {
            "q_proj": {"kernel": jnp.asarray(np.stack(qs))},
            "k_proj": {"kernel": jnp.asarray(np.stack(ks))},
            "v_proj": {"kernel": jnp.asarray(np.stack(vs))},
            "o_proj": lin(p + ".self_attention.dense.weight"),
            "fc1": lin(p + ".mlp.dense_h_to_4h.weight"),
            "fc2": lin(p + ".mlp.dense_4h_to_h.weight"),
        }
        if cfg.dual_ln:
            layers["ln1"] = ln(p + ".ln_attn.weight", p + ".ln_attn.bias")
            layers["ln2"] = ln(p + ".ln_mlp.weight", p + ".ln_mlp.bias")
        else:
            layers["ln1"] = ln(p + ".input_layernorm.weight",
                               p + ".input_layernorm.bias")
            if not cfg.parallel_attn:
                # sequential residual (falcon-rw): the model consumes ln2
                layers["ln2"] = ln(p + ".post_attention_layernorm.weight",
                                   p + ".post_attention_layernorm.bias")
        return {
            "embed": {"embedding": jnp.asarray(t("transformer.word_embeddings.weight"))},
            "layers": layers,
            "norm_f": {"scale": jnp.asarray(t("transformer.ln_f.weight")),
                       "bias": jnp.asarray(t("transformer.ln_f.bias"))},
        }

    if fam == "gptj":
        p = "transformer.h.{}"
        return {
            "embed": {"embedding": jnp.asarray(t("transformer.wte.weight"))},
            "layers": {
                "ln1": ln(p + ".ln_1.weight", p + ".ln_1.bias"),
                "q_proj": lin(p + ".attn.q_proj.weight"),
                "k_proj": lin(p + ".attn.k_proj.weight"),
                "v_proj": lin(p + ".attn.v_proj.weight"),
                "o_proj": lin(p + ".attn.out_proj.weight"),
                "fc1": lin(p + ".mlp.fc_in.weight", p + ".mlp.fc_in.bias"),
                "fc2": lin(p + ".mlp.fc_out.weight", p + ".mlp.fc_out.bias"),
            },
            "norm_f": {"scale": jnp.asarray(t("transformer.ln_f.weight")),
                       "bias": jnp.asarray(t("transformer.ln_f.bias"))},
            "lm_head": {"kernel": jnp.asarray(t("lm_head.weight").T),
                        "bias": jnp.asarray(t("lm_head.bias"))},
        }

    if fam == "phi":
        p = "model.layers.{}"
        params = {
            "embed": {"embedding": jnp.asarray(t("model.embed_tokens.weight"))},
            "layers": {
                "ln1": ln(p + ".input_layernorm.weight", p + ".input_layernorm.bias"),
                "q_proj": lin(p + ".self_attn.q_proj.weight", p + ".self_attn.q_proj.bias"),
                "k_proj": lin(p + ".self_attn.k_proj.weight", p + ".self_attn.k_proj.bias"),
                "v_proj": lin(p + ".self_attn.v_proj.weight", p + ".self_attn.v_proj.bias"),
                "o_proj": lin(p + ".self_attn.dense.weight", p + ".self_attn.dense.bias"),
                "fc1": lin(p + ".mlp.fc1.weight", p + ".mlp.fc1.bias"),
                "fc2": lin(p + ".mlp.fc2.weight", p + ".mlp.fc2.bias"),
            },
            "norm_f": {"scale": jnp.asarray(t("model.final_layernorm.weight")),
                       "bias": jnp.asarray(t("model.final_layernorm.bias"))},
            "lm_head": {"kernel": jnp.asarray(t("lm_head.weight").T),
                        "bias": jnp.asarray(t("lm_head.bias"))},
        }
        return params

    raise ValueError(f"no converter for family {fam!r}")


def load_hf_model(model_name_or_path: str, dtype=None, **overrides):
    """Full load: config + weights → (CausalLM, params).

    Works offline when ``model_name_or_path`` is a local directory with
    config.json + pytorch_model.bin / safetensors.
    """
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(model_name_or_path)
    model = from_pretrained_config(hf_cfg, **overrides)
    hf_model = AutoModelForCausalLM.from_pretrained(model_name_or_path,
                                                    torch_dtype="float32")
    fam = policy_for(hf_cfg)
    if fam in NATIVE_FAMILIES:
        params = convert_llama_state_dict(hf_model.state_dict(), model.config)
    else:
        params = convert_arch_state_dict(hf_model.state_dict(), model.config,
                                         fam)
    if dtype is not None:
        import jax

        params = jax.tree.map(lambda x: x.astype(dtype), params)
    return model, params


def tp_model_init(model: CausalLM, params: Any, tp_size: int, dtype=None):
    """Reference: deepspeed.tp_model_init (deepspeed/__init__.py:369) +
    TpTrainingManager (runtime/tensor_parallel/tp_manager.py:12): place the
    model's params TP-sharded for training."""
    import jax
    from jax.sharding import NamedSharding

    from ..runtime.topology import TopologyConfig, get_topology, initialize_mesh

    topo = get_topology()
    if topo.get_tensor_parallel_world_size() != tp_size:
        topo = initialize_mesh(TopologyConfig(tensor=tp_size), force=True)
    specs = model.partition_specs
    placed = jax.tree.map(
        lambda p, s: jax.device_put(
            p if dtype is None else p.astype(dtype), NamedSharding(topo.mesh, s)),
        params, specs, is_leaf=lambda x: hasattr(x, "ndim"))
    return model, placed
