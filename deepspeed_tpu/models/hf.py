"""HuggingFace model-family support.

Reference analogues: ``module_inject/replace_policy.py`` + ``containers/``
(BERT/GPT/LLaMA/OPT/BLOOM/Falcon/Qwen/Mistral/Mixtral policies) and the v2
``model_implementations`` per-arch directories, plus AutoTP
(``module_inject/auto_tp.py:192``) and ``tp_model_init``
(deepspeed/__init__.py:369).

TPU design: instead of monkey-patching torch modules, each supported HF
architecture maps to a :class:`TransformerConfig` ("policy") and a weight
converter that reads an HF torch ``state_dict`` (CPU torch is in the image)
into this framework's parameter pytree.  TP then falls out of
``partition_specs`` — the AutoTP row/col analysis is already encoded there.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..utils.logging import logger
from .transformer import CausalLM, TransformerConfig

#: HF architecture name → config-field mapping ("policy map")
_ARCH_POLICIES = {
    "LlamaForCausalLM": "llama",
    "MistralForCausalLM": "llama",
    "Qwen2ForCausalLM": "llama",
    "GPT2LMHeadModel": "gpt2",
    "GPTJForCausalLM": "gptj",
    "OPTForCausalLM": "opt",
    "BloomForCausalLM": "bloom",
    "FalconForCausalLM": "falcon",
    "MixtralForCausalLM": "mixtral",
}


def policy_for(hf_config: Any) -> str:
    archs = getattr(hf_config, "architectures", None) or []
    for a in archs:
        if a in _ARCH_POLICIES:
            return _ARCH_POLICIES[a]
    mt = getattr(hf_config, "model_type", "")
    for name, fam in (("llama", "llama"), ("mistral", "llama"), ("qwen2", "llama"),
                      ("gpt2", "gpt2"), ("opt", "opt"), ("bloom", "bloom"),
                      ("falcon", "falcon"), ("mixtral", "mixtral")):
        if mt == name:
            return fam
    raise ValueError(f"unsupported HF architecture: {archs or mt}")


def config_from_hf(hf_config: Any, **overrides) -> TransformerConfig:
    """HF config → TransformerConfig (the per-arch 'container' policy)."""
    fam = policy_for(hf_config)
    g = lambda *names, default=None: next(
        (getattr(hf_config, n) for n in names if getattr(hf_config, n, None)
         is not None), default)
    hidden = g("hidden_size", "n_embd", default=768)
    heads = g("num_attention_heads", "n_head", default=12)
    kw = dict(
        vocab_size=g("vocab_size", default=32000),
        hidden_size=hidden,
        intermediate_size=g("intermediate_size", "n_inner", default=4 * hidden),
        num_layers=g("num_hidden_layers", "n_layer", default=12),
        num_heads=heads,
        num_kv_heads=g("num_key_value_heads", default=heads),
        max_seq_len=g("max_position_embeddings", "n_positions", default=2048),
        rope_theta=g("rope_theta", default=10000.0),
        norm_eps=g("rms_norm_eps", "layer_norm_epsilon", default=1e-5),
        tie_embeddings=bool(g("tie_word_embeddings", default=False)),
    )
    if fam in ("gpt2", "opt", "bloom"):
        logger.warning(
            f"{fam}: learned-positional/LayerNorm families run on the "
            f"Llama-recipe compute path (RoPE+RMSNorm); exact-architecture "
            f"kernels for them land with the conversion test suite")
    kw.update(overrides)
    return TransformerConfig(**kw)


def from_pretrained_config(name_or_config: Any, **overrides) -> CausalLM:
    """Build a CausalLM from an HF config object or model-name string."""
    cfg = name_or_config
    if isinstance(name_or_config, str):
        from transformers import AutoConfig

        cfg = AutoConfig.from_pretrained(name_or_config)
    return CausalLM(config_from_hf(cfg, **overrides))


# --------------------------------------------------------------------- #
# Weight conversion (HF torch state_dict → framework pytree)
# --------------------------------------------------------------------- #
def convert_llama_state_dict(sd: Dict[str, Any], cfg: TransformerConfig) -> Dict:
    """Llama/Mistral/Qwen2 HF checkpoint → stacked-layer pytree."""
    import jax.numpy as jnp

    def t(name):
        w = sd[name]
        if hasattr(w, "numpy"):
            w = w.float().numpy()
        return np.asarray(w, np.float32)

    L = cfg.num_layers

    def stack(fmt, transpose=True):
        ws = [t(fmt.format(i)) for i in range(L)]
        arr = np.stack([w.T if transpose else w for w in ws])
        return jnp.asarray(arr)

    params = {
        "embed": {"embedding": jnp.asarray(t("model.embed_tokens.weight"))},
        "layers": {
            "attn_norm": {"scale": stack("model.layers.{}.input_layernorm.weight",
                                         transpose=False)},
            "q_proj": {"kernel": stack("model.layers.{}.self_attn.q_proj.weight")},
            "k_proj": {"kernel": stack("model.layers.{}.self_attn.k_proj.weight")},
            "v_proj": {"kernel": stack("model.layers.{}.self_attn.v_proj.weight")},
            "o_proj": {"kernel": stack("model.layers.{}.self_attn.o_proj.weight")},
            "mlp_norm": {"scale": stack("model.layers.{}.post_attention_layernorm.weight",
                                        transpose=False)},
            "gate_proj": {"kernel": stack("model.layers.{}.mlp.gate_proj.weight")},
            "up_proj": {"kernel": stack("model.layers.{}.mlp.up_proj.weight")},
            "down_proj": {"kernel": stack("model.layers.{}.mlp.down_proj.weight")},
        },
        "norm_f": {"scale": jnp.asarray(t("model.norm.weight"))},
    }
    if not cfg.tie_embeddings and "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": jnp.asarray(t("lm_head.weight").T)}
    return params


def load_hf_model(model_name_or_path: str, dtype=None, **overrides):
    """Full load: config + weights → (CausalLM, params).

    Works offline when ``model_name_or_path`` is a local directory with
    config.json + pytorch_model.bin / safetensors.
    """
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(model_name_or_path)
    model = from_pretrained_config(hf_cfg, **overrides)
    hf_model = AutoModelForCausalLM.from_pretrained(model_name_or_path,
                                                    torch_dtype="float32")
    params = convert_llama_state_dict(hf_model.state_dict(), model.config)
    if dtype is not None:
        import jax

        params = jax.tree.map(lambda x: x.astype(dtype), params)
    return model, params


def tp_model_init(model: CausalLM, params: Any, tp_size: int, dtype=None):
    """Reference: deepspeed.tp_model_init (deepspeed/__init__.py:369) +
    TpTrainingManager (runtime/tensor_parallel/tp_manager.py:12): place the
    model's params TP-sharded for training."""
    import jax
    from jax.sharding import NamedSharding

    from ..runtime.topology import TopologyConfig, get_topology, initialize_mesh

    topo = get_topology()
    if topo.get_tensor_parallel_world_size() != tp_size:
        topo = initialize_mesh(TopologyConfig(tensor=tp_size), force=True)
    specs = model.partition_specs
    placed = jax.tree.map(
        lambda p, s: jax.device_put(
            p if dtype is None else p.astype(dtype), NamedSharding(topo.mesh, s)),
        params, specs, is_leaf=lambda x: hasattr(x, "ndim"))
    return model, placed
