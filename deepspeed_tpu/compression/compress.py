"""Compression library (reference: deepspeed/compression/compress.py
init_compression/redundancy_clean, basic_layer.py:121 LinearLayer_Compress,
scheduler.py).

The reference swaps nn.Linear for compress-aware modules; functionally that is
a pair of pytree transforms:

  * :func:`init_compression` — given params + compression config, returns
    (params, CompressionSpec) where the spec records which leaves get which
    treatment (weight quantization bits, sparse/row/head pruning ratios,
    layer reduction);
  * :func:`apply_compression` — quantize-dequantize (QAT fake-quant) and
    pruning masks applied to params — called inside the loss fn each step
    (training-time) or once at export (redundancy_clean).
"""
from __future__ import annotations

import dataclasses
import fnmatch
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class LeafCompression:
    quantize_bits: Optional[int] = None           # weight fake-quant bits
    quantize_groups: int = 1
    sparse_ratio: Optional[float] = None          # unstructured pruning
    row_ratio: Optional[float] = None             # structured row pruning
    head_ratio: Optional[float] = None
    num_heads: Optional[int] = None
    channel_ratio: Optional[float] = None         # output-channel pruning
    act_bits: Optional[int] = None                # activation quantization


CompressionSpec = Dict[str, LeafCompression]


def _match(patterns: List[str], path: str) -> bool:
    """Glob-style module matching (reference uses substring/regex on module
    names; globs are the dict-pytree equivalent)."""
    return any(fnmatch.fnmatch(path, p) or fnmatch.fnmatch(path, p + "*") or
               (not any(ch in p for ch in "*?[") and p in path)
               for p in patterns)


def init_compression(params: Any, compression_config: Dict[str, Any],
                     mpu=None) -> Tuple[Any, CompressionSpec]:
    """Build the per-leaf compression spec from a DeepSpeed-style config
    (weight_quantization / sparse_pruning / row_pruning / head_pruning
    sections with shared_parameters + different_groups)."""
    spec: CompressionSpec = {}
    flat = _flatten_paths(params)

    def section(name):
        sec = compression_config.get(name, {})
        shared = sec.get("shared_parameters", {})
        groups = sec.get("different_groups", {})
        return sec, shared, groups

    wq, wq_shared, wq_groups = section("weight_quantization")
    if wq_shared.get("enabled", False):
        for gname, g in wq_groups.items():
            bits = g.get("params", {}).get("start_bits", 8)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    spec.setdefault(path, LeafCompression()).quantize_bits = int(bits)
                    spec[path].quantize_groups = wq_shared.get("quantize_groups", 1)

    sp, sp_shared, sp_groups = section("sparse_pruning")
    if sp_shared.get("enabled", False):
        for gname, g in sp_groups.items():
            ratio = g.get("params", {}).get("dense_ratio", 0.5)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    spec.setdefault(path, LeafCompression()).sparse_ratio = float(ratio)

    rp, rp_shared, rp_groups = section("row_pruning")
    if rp_shared.get("enabled", False):
        for gname, g in rp_groups.items():
            ratio = g.get("params", {}).get("dense_ratio", 0.5)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    spec.setdefault(path, LeafCompression()).row_ratio = float(ratio)

    hp, hp_shared, hp_groups = section("head_pruning")
    if hp_shared.get("enabled", False):
        for gname, g in hp_groups.items():
            ratio = g.get("params", {}).get("dense_ratio", 0.5)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    lc = spec.setdefault(path, LeafCompression())
                    lc.head_ratio = float(ratio)
                    lc.num_heads = hp_shared.get("num_heads")

    cp, cp_shared, cp_groups = section("channel_pruning")
    if cp_shared.get("enabled", False):
        for gname, g in cp_groups.items():
            ratio = g.get("params", {}).get("dense_ratio", 0.5)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    spec.setdefault(path, LeafCompression()).channel_ratio = \
                        float(ratio)

    aq, aq_shared, aq_groups = section("activation_quantization")
    if aq_shared.get("enabled", False):
        for gname, g in aq_groups.items():
            bits = g.get("params", {}).get("bits", 8)
            for path in flat:
                if _match(g.get("modules", ["*"]), path):
                    spec.setdefault(path, LeafCompression()).act_bits = int(bits)

    lr_cfg = compression_config.get("layer_reduction", {})
    if lr_cfg.get("enabled", False):
        params = apply_layer_reduction(params, lr_cfg)
    return params, spec


def apply_layer_reduction(params: Any, lr_cfg: Dict[str, Any]) -> Any:
    """Layer reduction / distillation init (reference compress.py
    student_initialization): slice stacked [L, ...] layer arrays down to
    ``teacher_layer`` indices (or the first ``keep_number`` layers)."""
    import numpy as np

    keep = lr_cfg.get("teacher_layer")
    if keep is None:
        keep = list(range(int(lr_cfg.get("keep_number", 1))))
    keep = np.asarray(keep, np.int32)

    # The layer axis is identified, not guessed: every stacked-layer leaf
    # shares dim0 == num_layers, so slice ONLY leaves matching that count
    # (an arbitrary dim0 > max(keep) could be a head or channel axis).
    num_layers = lr_cfg.get("num_layers")
    if num_layers is None:
        dims = []

        def collect(path, w):
            if hasattr(w, "ndim") and w.ndim >= 1 and "layers" in path:
                dims.append(int(w.shape[0]))
            return w

        _map_with_paths(params, collect)
        if not dims:
            return params
        num_layers = max(set(dims), key=dims.count)
    if keep.max() >= num_layers:
        raise ValueError(
            f"layer_reduction teacher_layer {keep.tolist()} out of range for "
            f"a {num_layers}-layer model")

    def maybe_slice(path, w):
        if not hasattr(w, "ndim") or w.ndim < 1:
            return w
        if "layers" in path and w.shape[0] == num_layers:
            return w[keep]
        return w

    return _map_with_paths(params, maybe_slice)


def head_mask(w: jnp.ndarray, dense_ratio: float, num_heads: int) -> jnp.ndarray:
    """Keep top heads by L2 norm.  ``w`` [..., D, H*hd] (column-parallel qkv
    layout; leading dims = stacked layers/experts get INDEPENDENT masks):
    mask whole head blocks of the output dim."""
    hd = w.shape[-1] // num_heads
    per_head = w.reshape(w.shape[:-1] + (num_heads, hd))      # [..., D, H, hd]
    norms = jnp.sqrt(jnp.sum(jnp.square(per_head), axis=(-3, -1)))  # [..., H]
    k = max(int(num_heads * dense_ratio), 1)
    thresh = jnp.sort(norms, axis=-1)[..., -k][..., None]
    mask = (norms >= thresh).astype(w.dtype)                  # [..., H]
    mask = jnp.repeat(mask, hd, axis=-1)                      # [..., H*hd]
    return mask[..., None, :]                                 # broadcast over D


def channel_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Keep top output channels (last dim) by L1 norm, ranked PER leading
    slice (each stacked layer/expert keeps its own strongest channels)."""
    norms = jnp.sum(jnp.abs(w), axis=-2)                      # [..., C]
    k = max(int(w.shape[-1] * dense_ratio), 1)
    thresh = jnp.sort(norms, axis=-1)[..., -k][..., None]
    mask = (norms >= thresh).astype(w.dtype)
    return mask[..., None, :]


def quantize_activation(x: jnp.ndarray, bits: int = 8) -> jnp.ndarray:
    """Activation fake-quant with STE (reference activation_quantization):
    call inside the model on the activations feeding a compressed layer."""
    return fake_quantize(x, bits, groups=1)


def activation_quantizer(spec: CompressionSpec, path: str):
    """The config-driven consumer of ``act_bits``: returns a function the
    model applies to the activation feeding the layer at ``path`` (identity
    when activation quantization isn't configured for it).

        aq = activation_quantizer(spec, "layers.fc1.kernel")
        h = aq(h); y = h @ w
    """
    lc = spec.get(path)
    if lc is None or lc.act_bits is None:
        return lambda x: x
    bits = lc.act_bits
    return lambda x: quantize_activation(x, bits)


def fake_quantize(w: jnp.ndarray, bits: int, groups: int = 1) -> jnp.ndarray:
    """Symmetric per-group QAT fake quantization with straight-through grads."""
    qmax = 2.0 ** (bits - 1) - 1
    flat = w.reshape(groups, -1)
    scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(flat / scale), -qmax, qmax) * scale
    dq = q.reshape(w.shape)
    return w + jax.lax.stop_gradient(dq - w)  # STE


def magnitude_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Keep top |dense_ratio| fraction by magnitude (unstructured)."""
    k = max(int(w.size * dense_ratio), 1)
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_mask(w: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Keep top rows by L1 norm (structured row pruning; dim 0)."""
    norms = jnp.sum(jnp.abs(w.reshape(w.shape[0], -1)), axis=1)
    k = max(int(w.shape[0] * dense_ratio), 1)
    thresh = jnp.sort(norms)[-k]
    mask = (norms >= thresh).astype(w.dtype)
    return mask.reshape((-1,) + (1,) * (w.ndim - 1))


def apply_compression(params: Any, spec: CompressionSpec) -> Any:
    """Apply the spec (inside the loss fn for QAT, or at export)."""
    flat = _flatten_paths(params)

    def transform(path, w):
        lc = spec.get(path)
        if lc is None or not hasattr(w, "ndim"):
            return w
        if lc.sparse_ratio is not None:
            w = w * jax.lax.stop_gradient(magnitude_mask(w, lc.sparse_ratio))
        if lc.row_ratio is not None and w.ndim >= 1:
            w = w * jax.lax.stop_gradient(row_mask(w, lc.row_ratio))
        if lc.head_ratio is not None and lc.num_heads and w.ndim >= 2:
            w = w * jax.lax.stop_gradient(
                head_mask(w, lc.head_ratio, lc.num_heads))
        if lc.channel_ratio is not None and w.ndim >= 2:
            w = w * jax.lax.stop_gradient(channel_mask(w, lc.channel_ratio))
        if lc.quantize_bits is not None:
            w = fake_quantize(w, lc.quantize_bits, lc.quantize_groups)
        return w

    return _map_with_paths(params, transform, flat)


def redundancy_clean(params: Any, spec: CompressionSpec) -> Any:
    """Materialize the compression permanently (reference redundancy_clean)."""
    return jax.tree.map(jax.lax.stop_gradient, apply_compression(params, spec))


def _flatten_paths(tree) -> List[str]:
    paths = []

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        else:
            paths.append(prefix)

    walk("", tree)
    return paths


def _map_with_paths(tree, fn, _paths=None):
    def walk(prefix, node):
        if isinstance(node, dict):
            return {k: walk(f"{prefix}.{k}" if prefix else str(k), v)
                    for k, v in node.items()}
        return fn(prefix, node)

    return walk("", tree)
