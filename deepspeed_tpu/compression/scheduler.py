"""Compression scheduler (reference: compression/scheduler.py —
``compression_scheduler.step()`` gates each method on its
``schedule_offset`` so e.g. pruning only kicks in after N warmup steps).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

from .compress import CompressionSpec, LeafCompression

#: spec field → config section whose shared_parameters carry the offset
_METHOD_SECTIONS = {
    "quantize_bits": "weight_quantization",
    "sparse_ratio": "sparse_pruning",
    "row_ratio": "row_pruning",
    "head_ratio": "head_pruning",
    "channel_ratio": "channel_pruning",
    "act_bits": "activation_quantization",
}


class CompressionScheduler:
    """Step-gates a :data:`CompressionSpec` by per-method schedule offsets."""

    def __init__(self, spec: CompressionSpec,
                 compression_config: Dict[str, Any]):
        self.spec = spec
        self.offsets = {
            field: int(compression_config.get(section, {})
                       .get("shared_parameters", {})
                       .get("schedule_offset", 0))
            for field, section in _METHOD_SECTIONS.items()
        }
        self.current_step = 0

    def step(self, n: int = 1) -> None:
        self.current_step += n

    def spec_at(self, step: int = None) -> CompressionSpec:
        """The spec with methods whose offset hasn't been reached disabled.

        Pass the result to :func:`apply_compression` inside the loss fn;
        re-derive per grad-accumulation boundary (cheap — host-side dict)."""
        step = self.current_step if step is None else step
        out: CompressionSpec = {}
        for path, lc in self.spec.items():
            gated = dataclasses.replace(lc)
            for field, offset in self.offsets.items():
                if step < offset:
                    setattr(gated, field, None)
            if any(getattr(gated, f) is not None for f in _METHOD_SECTIONS):
                out[path] = gated
        return out
