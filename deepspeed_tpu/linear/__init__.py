from .optimized_linear import (
    LoRAConfig,
    LoRAOptimizedLinear,
    OptimizedLinear,
    QuantizationConfig,
    dequantize_int8,
    quantize_int8,
)

__all__ = ["OptimizedLinear", "LoRAOptimizedLinear", "LoRAConfig",
           "QuantizationConfig", "quantize_int8", "dequantize_int8"]
