"""OptimizedLinear: quantized base weight + LoRA adapters, shard-aware.

Reference: ``deepspeed/linear/optimized_linear.py:18`` with ``LoRAConfig`` /
``QuantizationConfig`` (``linear/config.py:13,39``).  Functional JAX version:
``init_params`` produces a frozen (optionally int8-quantized) base kernel plus
trainable low-rank A/B factors; ``apply`` fuses dequant into the matmul epilog
(XLA fuses the scale multiply).  The base weight can be sharded over the ZeRO
axes like the reference's DP-sharded base weight.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class LoRAConfig:
    """Reference: linear/config.py:13."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: Any = None


@dataclasses.dataclass
class QuantizationConfig:
    """Reference: linear/config.py:39."""

    q_bits: int = 8
    rounding: str = "nearest"
    mantissa_bits: int = 3
    group_size: int = 512
    group_dim: int = 0


def quantize_int8(w: jnp.ndarray, group_size: int = 512):
    """Groupwise symmetric int8 quantization along dim 0."""
    in_dim, out_dim = w.shape
    groups = max(in_dim // group_size, 1)
    gsize = in_dim // groups
    wg = w[:groups * gsize].reshape(groups, gsize, out_dim)
    scale = jnp.max(jnp.abs(wg), axis=1, keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(wg / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32):
    groups, gsize, out_dim = q.shape
    return (q.astype(jnp.float32) * scale).reshape(groups * gsize, out_dim).astype(dtype)


class OptimizedLinear:
    def __init__(self, input_dim: int, output_dim: int,
                 lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias: bool = False, dtype=jnp.bfloat16):
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.lora = lora_config or LoRAConfig()
        self.quant = quantization_config
        self.bias = bias
        self.dtype = dtype

    def init_params(self, key: jax.Array, base_weight: Optional[jnp.ndarray] = None) -> Dict:
        k1, k2, k3 = jax.random.split(key, 3)
        if base_weight is None:
            base_weight = jax.random.normal(k1, (self.input_dim, self.output_dim)) \
                / math.sqrt(self.input_dim)
        params: Dict[str, Any] = {}
        if self.quant is not None:
            q, scale = quantize_int8(base_weight, self.quant.group_size)
            params["base"] = {"q": q, "scale": scale}
        else:
            params["base"] = {"kernel": base_weight.astype(self.dtype)}
        r = self.lora.lora_r
        params["lora_A"] = (jax.random.normal(k2, (self.input_dim, r)) /
                            math.sqrt(self.input_dim)).astype(jnp.float32)
        params["lora_B"] = jnp.zeros((r, self.output_dim), jnp.float32)
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def apply(self, params: Dict, x: jnp.ndarray) -> jnp.ndarray:
        if "q" in params["base"]:
            w = dequantize_int8(params["base"]["q"], params["base"]["scale"], x.dtype)
        else:
            w = params["base"]["kernel"].astype(x.dtype)
        out = x @ w
        scaling = self.lora.lora_alpha / self.lora.lora_r
        out = out + (x @ params["lora_A"].astype(x.dtype)) @ \
            params["lora_B"].astype(x.dtype) * scaling
        if self.bias:
            out = out + params["bias"].astype(x.dtype)
        return out

    __call__ = apply

    def trainable_filter(self, params: Dict) -> Dict:
        """Mask pytree: True for trainable leaves (LoRA + bias), False for base.

        Feed to ``optax.masked`` so the optimizer only touches adapters —
        the reference freezes the base weight the same way.
        """
        return jax.tree.map(lambda _: False, params) | {
            "lora_A": True, "lora_B": True,
            **({"bias": True} if self.bias else {}),
        }


class LoRAOptimizedLinear(OptimizedLinear):
    """Reference class name alias (linear/optimized_linear.py:87)."""
