"""Fused LAMB (reference ⚙: csrc/lamb/fused_lamb_cuda.cpp +
fused_lamb_cuda_kernel.cu, bound via deepspeed/ops/lamb/)."""
from .fused_lamb import FusedLambState, fused_lamb, fused_lamb_update

__all__ = ["fused_lamb", "fused_lamb_update", "FusedLambState"]
