"""Pallas fused LAMB (reference ⚙: csrc/lamb/fused_lamb_cuda_kernel.cu).

LAMB = Adam-style moment update + per-tensor trust ratio
``||p|| / ||update||``.  The heavy streaming pass (moments + raw update, one
read-modify-write over p/g/m/v) runs as a Pallas kernel; the two scalar
norms and the final trust-scaled parameter write are tiny elementwise ops
XLA fuses into the same program — matching the CUDA kernel's two-phase
reduction structure without a hand-written cross-block reduction.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..adam.fused_adam import _interpret, _tile_plan


def _lamb_raw_kernel(p_ref, g_ref, m_ref, v_ref, bc1_ref, bc2_ref,
                     u_out, m_out, v_out, *, beta1, beta2, eps, weight_decay):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    bc1 = bc1_ref[0, 0]
    bc2 = bc2_ref[0, 0]
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if weight_decay:
        u = u + weight_decay * p
    u_out[:] = u
    m_out[:] = m_new
    v_out[:] = v_new


def fused_lamb_update(p, g, m, v, step, lr=1e-3, beta1=0.9, beta2=0.999,
                      eps=1e-6, weight_decay=0.0,
                      min_trust: float = 0.01, max_trust: float = 10.0):
    """Single-array fused LAMB step → (p', m', v')."""
    shape, dtype = p.shape, p.dtype
    rows, width, flat2d, unflat, spec, grid = _tile_plan(shape)
    pf, gf, mf, vf = map(flat2d, (p, g, m, v))
    t = step.astype(jnp.float32) + 1.0
    bc1 = (1.0 - beta1 ** t).reshape(1, 1)
    bc2 = (1.0 - beta2 ** t).reshape(1, 1)

    u, m2, v2 = pl.pallas_call(
        functools.partial(_lamb_raw_kernel, beta1=beta1, beta2=beta2, eps=eps,
                          weight_decay=weight_decay),
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, width), jnp.float32)] * 3,
        interpret=_interpret(),
    )(pf, gf, mf, vf, bc1, bc2)

    u, m2, v2 = unflat(u), unflat(m2), unflat(v2)

    p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(u)))
    trust = jnp.where((p_norm > 0) & (u_norm > 0),
                      p_norm / jnp.maximum(u_norm, 1e-12), 1.0)
    trust = jnp.clip(trust, min_trust, max_trust)
    return (p.astype(jnp.float32) - lr * trust * u).astype(dtype), m2, v2


class FusedLambState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def fused_lamb(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-6,
               weight_decay=0.0) -> optax.GradientTransformation:
    """Optax-compatible fused LAMB (returns additive updates)."""
    from ..adam.fused_adam import optax_wrap

    def leaf(lr, count, p, g, m, v):
        return fused_lamb_update(p, g, m, v, count, lr=lr, beta1=b1, beta2=b2,
                                 eps=eps, weight_decay=weight_decay)

    return optax_wrap(leaf, FusedLambState, 2, learning_rate)
