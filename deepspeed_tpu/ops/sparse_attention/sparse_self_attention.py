"""Sparse self-attention (reference: deepspeed/ops/sparse_attention/
sparse_self_attention.py + bert_sparse_self_attention.py — Triton block-sparse
matmul/softmax).

TPU implementation: two paths share the layout classes.  The Pallas
block-sparse kernel (block_sparse_kernel.py, use_kernel=True) skips both
compute and DMA for masked blocks and is fully differentiable (custom_vjp
dq/dkv kernels reuse the layout gating) — training and serving both take
it; the masked-dense path remains for the rpe/padding/attn-mask extras and
as the numerics oracle.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import DenseSparsityConfig, SparsityConfig


class SparseSelfAttention:
    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config or DenseSparsityConfig(num_heads=1)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._mask_cache = {}

    def token_mask(self, seq_len: int) -> jnp.ndarray:
        """[heads, S, S] bool mask expanded from the block layout."""
        if seq_len not in self._mask_cache:
            layout = self.sparsity_config.make_layout(seq_len)   # [H, n, n]
            b = self.sparsity_config.block
            mask = np.kron(layout, np.ones((b, b), dtype=bool))
            self._mask_cache[seq_len] = jnp.asarray(mask)
        return self._mask_cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None, use_kernel: bool = False):
        """q/k/v: [B, H, S, hd] (reference layout). Returns [B, H, S, hd].

        ``use_kernel=True`` takes the Pallas block-sparse kernel (masked
        blocks skip both compute and DMA; differentiable — the custom_vjp
        dq/dkv kernels walk the same layout) but not the
        rpe/padding/attn-mask extras; those keep the masked-dense path."""
        B, H, S, hd = query.shape
        if use_kernel:
            assert rpe is None and key_padding_mask is None and \
                attn_mask is None, "kernel path takes the plain layout only"
            from .block_sparse_kernel import (
                block_sparse_attention,
                build_fetch_table,
            )

            # layout + fetch table are static per (config, seq_len): cache
            # like the dense path's token mask (the table rebuild is O(H·n²)
            # host work the serving fast path must not repeat per call)
            if ("layout", S) not in self._mask_cache:
                layout = np.asarray(self.sparsity_config.make_layout(S))
                self._mask_cache[("layout", S)] = (layout,
                                                   build_fetch_table(layout))
            layout, table = self._mask_cache[("layout", S)]
            return block_sparse_attention(query, key, value, layout,
                                          self.sparsity_config.block,
                                          table=table)
        mask = self.token_mask(S)                                # [Hl, S, S]
        if mask.shape[0] == 1:
            mask = jnp.broadcast_to(mask, (H, S, S))
        scores = jnp.einsum("bhqd,bhkd->bhqk", query, key) / jnp.sqrt(
            jnp.asarray(hd, query.dtype))
        if rpe is not None:
            scores = scores + rpe
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, scores.dtype)
        scores = jnp.where(mask[None], scores, neg)
        if key_padding_mask is not None:
            pad = key_padding_mask[:, None, None, :]
            scores = scores + pad if self.key_padding_mask_mode == "add" else \
                jnp.where(pad.astype(bool), scores, neg)
        if attn_mask is not None:
            scores = scores * attn_mask if self.attn_mask_mode == "mul" else \
                scores + attn_mask
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(query.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, value)


class BertSparseSelfAttention(SparseSelfAttention):
    """Reference class alias (bert_sparse_self_attention.py)."""
