"""Pallas block-sparse attention kernel (reference ⚙: the Triton
block-sparse matmul/softmax under deepspeed/ops/sparse_attention/).

The layout classes (sparsity_config.py) produce a per-head [nq, nk] block
layout; round 1 expanded it to a token mask over DENSE attention (correct,
but pays full O(S²) compute + HBM).  This kernel makes the sparsity real:

  * compute runs only where ``layout[h, iq, ik]`` is set (``pl.when``);
  * a precomputed FETCH TABLE (static per layout) clamps each masked grid
    step's kv index map to the previously fetched block — Pallas skips the
    DMA for an unchanged block, so masked blocks cost neither bandwidth nor
    MXU work (the same trick as the causal/paged kernels).

Forward-only: training through sparse attention keeps the masked-dense path
(whose backward is exact); serving/inference takes this kernel.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def build_fetch_table(layout: np.ndarray) -> np.ndarray:
    """[H, nq, nk] layout → same-shape table of kv block indices to fetch at
    each grid step: the block itself when active, else the last active block
    of the row (no new DMA).  Rows with no active block fetch block 0."""
    H, nq, nk = layout.shape
    table = np.zeros((H, nq, nk), np.int32)
    for h in range(H):
        for i in range(nq):
            row = np.nonzero(layout[h, i])[0]
            last = int(row[0]) if len(row) else 0
            for j in range(nk):
                if layout[h, i, j]:
                    last = j
                table[h, i, j] = last
    return table


def _bs_kernel(layout_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
               acc, m_scr, l_scr, *, scale, block, seq_len):
    h, iq, ik = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(layout_ref[h, iq, ik] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = ik * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        s = jnp.where(k_pos < seq_len, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc[:] = acc[:] * alpha + jnp.dot(p, v,
                                          preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)


def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           layout: np.ndarray, block: int,
                           scale: Optional[float] = None,
                           table: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Block-sparse attention over [B, H, S, hd] with a static per-head
    [H, nq, nk] block layout (forward only).  Pass a cached ``table`` from
    :func:`build_fetch_table` to skip the O(H·n²) host rebuild per call."""
    B, H, S, hd = q.shape
    layout = np.asarray(layout)
    if layout.ndim == 2:
        layout = layout[None]
    if layout.shape[0] != H:
        assert layout.shape[0] == 1, \
            f"layout heads {layout.shape[0]} != tensor heads {H}"
        layout = np.broadcast_to(layout, (H,) + layout.shape[1:])
    nq, nk = layout.shape[1:]
    assert nq * block >= S and nk * block >= S, (layout.shape, block, S)
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    def pad_to(x, blocks):
        return jnp.pad(x, ((0, 0), (0, 0), (0, blocks * block - S), (0, 0)))

    qp = pad_to(q, nq)
    kp, vp = pad_to(k, nk), pad_to(v, nk)
    if table is None:
        table = build_fetch_table(layout)
    elif table.shape[0] != H:
        assert table.shape[0] == 1, table.shape
        table = np.broadcast_to(table, (H,) + table.shape[1:])

    out = pl.pallas_call(
        functools.partial(_bs_kernel, scale=scale, block=block, seq_len=S),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, block, hd),
                             lambda b, h, i, j, lay, tab: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block, hd),
                             lambda b, h, i, j, lay, tab: (b, h, tab[h, i, j], 0)),
                pl.BlockSpec((1, 1, block, hd),
                             lambda b, h, i, j, lay, tab: (b, h, tab[h, i, j], 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block, hd),
                                   lambda b, h, i, j, lay, tab: (b, h, i, 0)),
            scratch_shapes=[
                pltpu.VMEM((block, hd), jnp.float32),
                pltpu.VMEM((block, 128), jnp.float32),
                pltpu.VMEM((block, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, nq * block, hd), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(layout, jnp.int32), jnp.asarray(table, jnp.int32),
      qp, kp, vp)
    return out[:, :, :S]
