"""Pallas block-sparse attention kernels (reference ⚙: the Triton
block-sparse matmul/softmax under deepspeed/ops/sparse_attention/ —
fwd AND bwd, matmul.py's sdd/dsd/dds modes).

The layout classes (sparsity_config.py) produce a per-head [nq, nk] block
layout; round 1 expanded it to a token mask over DENSE attention (correct,
but pays full O(S²) compute + HBM).  These kernels make the sparsity real:

  * compute runs only where ``layout[h, iq, ik]`` is set (``pl.when``);
  * a precomputed FETCH TABLE (static per layout) clamps each masked grid
    step's kv index map to the previously fetched block — Pallas skips the
    DMA for an unchanged block, so masked blocks cost neither bandwidth nor
    MXU work (the same trick as the causal/paged kernels).

Training goes through the SAME sparsity structure: ``custom_vjp`` with
Pallas dq and dk/dv kernels that reuse the layout gating and fetch tables
(dkv walks the transposed layout), so backward cost also scales with
layout density rather than O(S²) — matching the reference, which trains
through its Triton kernels.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def build_fetch_table(layout: np.ndarray) -> np.ndarray:
    """[H, nq, nk] layout → same-shape table of kv block indices to fetch at
    each grid step: the block itself when active, else the last active block
    of the row (no new DMA).  Rows with no active block fetch block 0."""
    H, nq, nk = layout.shape
    table = np.zeros((H, nq, nk), np.int32)
    for h in range(H):
        for i in range(nq):
            row = np.nonzero(layout[h, i])[0]
            last = int(row[0]) if len(row) else 0
            for j in range(nk):
                if layout[h, i, j]:
                    last = j
                table[h, i, j] = last
    return table


_STATS_LANES = 128    # lse/delta carry a lane dim so blocks tile on Mosaic


def _bs_kernel(layout_ref, table_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
               acc, m_scr, l_scr, *, scale, block, seq_len):
    h, iq, ik = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(layout_ref[h, iq, ik] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = ik * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        s = jnp.where(k_pos < seq_len, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc[:] = acc[:] * alpha + jnp.dot(p, v,
                                          preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)
        # rows with no active block keep lse = -inf; bwd never touches them
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l_safe),
                                         lse_ref.shape[2:])


def _bs_kernel_nolse(layout_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                     acc, m_scr, l_scr, *, scale, block, seq_len):
    """Inference-primal variant: identical online-softmax walk, no lse
    residual output (see _bs_fwd)."""
    h, iq, ik = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(layout_ref[h, iq, ik] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = ik * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        s = jnp.where(k_pos < seq_len, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc[:] = acc[:] * alpha + jnp.dot(p, v,
                                          preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)


def _bs_dq_kernel(layout_ref, table_ref, q_ref, k_ref, v_ref, do_ref,
                  lse_ref, delta_ref, dq_ref, dq_acc, *, scale, block,
                  seq_len):
    h, iq, ik = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(layout_ref[h, iq, ik] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = ik * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        p = jnp.where(k_pos < seq_len, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _write():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bs_dkv_kernel(layout_t_ref, table_t_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                   scale, block, seq_len):
    # kv-blocks outer, q-blocks inner: gating/fetch walk the TRANSPOSED
    # layout, so masked q blocks skip DMA exactly like masked kv blocks in
    # the forward.
    h, ik, iq = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(layout_t_ref[h, ik, iq] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = ik * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1)
        p = jnp.where(k_pos < seq_len, jnp.exp(s - lse), 0.0)
        dv_acc[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


class _StaticArr:
    """Hashable holder so numpy layout/fetch tables can ride custom_vjp
    nondiff_argnums (hash by content → jit caches correctly per layout)."""

    __slots__ = ("arr", "_h")

    def __init__(self, arr):
        self.arr = np.ascontiguousarray(arr)
        self._h = hash((self.arr.shape, self.arr.tobytes()))

    def __hash__(self):
        return self._h

    def __eq__(self, other):
        return isinstance(other, _StaticArr) and \
            self.arr.shape == other.arr.shape and \
            np.array_equal(self.arr, other.arr)


#: layout-content → (table, layoutᵀ, tableᵀ) holders; see block_sparse_attention
_PREPARED_CACHE: dict = {}


def _q_specs(block, hd):
    return pl.BlockSpec((1, 1, block, hd),
                        lambda b, h, i, j, lay, tab: (b, h, i, 0))


def _kv_specs(block, hd):
    return pl.BlockSpec((1, 1, block, hd),
                        lambda b, h, i, j, lay, tab: (b, h, tab[h, i, j], 0))


def _bs_fwd(q, k, v, layout_h, table_h, block, scale, seq_len,
            want_lse: bool):
    """Forward pallas call.  ``want_lse=False`` (the inference primal) uses
    the lse-free kernel — the residual is a [B,H,S,128] f32 HBM write as
    large as the output itself, so it must not be paid when no gradient
    will ever be taken."""
    B, H, _, hd = q.shape
    layout, table = layout_h.arr, table_h.arr
    nq, nk = layout.shape[1:]
    out_specs = _q_specs(block, hd)
    out_shape = jax.ShapeDtypeStruct((B, H, nq * block, hd), q.dtype)
    kernel = _bs_kernel_nolse
    if want_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((1, 1, block, _STATS_LANES),
                                  lambda b, h, i, j, lay, tab: (b, h, i, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((B, H, nq * block, _STATS_LANES),
                                          jnp.float32)]
        kernel = _bs_kernel
    res = pl.pallas_call(
        functools.partial(kernel, scale=scale, block=block,
                          seq_len=seq_len),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nq, nk),
            in_specs=[_q_specs(block, hd), _kv_specs(block, hd),
                      _kv_specs(block, hd)],
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((block, hd), jnp.float32),
                pltpu.VMEM((block, 128), jnp.float32),
                pltpu.VMEM((block, 128), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        interpret=_interpret(),
    )(jnp.asarray(layout, jnp.int32), jnp.asarray(table, jnp.int32), q, k, v)
    if want_lse:
        out, lse = res
        return out, lse[..., :1]
    return res, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _bs_attn(q, k, v, layout_h, table_h, layout_t_h, table_t_h, block, scale,
             seq_len):
    out, _ = _bs_fwd(q, k, v, layout_h, table_h, block, scale, seq_len,
                     want_lse=False)
    return out


def _bs_fwd_rule(q, k, v, layout_h, table_h, layout_t_h, table_t_h, block,
                 scale, seq_len):
    out, lse = _bs_fwd(q, k, v, layout_h, table_h, block, scale, seq_len,
                       want_lse=True)
    return out, (q, k, v, out, lse)


def _bs_bwd_rule(layout_h, table_h, layout_t_h, table_t_h, block, scale,
                 seq_len, res, do):
    q, k, v, out, lse = res
    B, H, Sq, hd = q.shape
    nq, nk = layout_h.arr.shape[1:]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                       # [B,H,Sq,1]
    stats = lambda x: jnp.broadcast_to(x, (B, H, Sq, _STATS_LANES))
    lse_b, delta_b = stats(lse), stats(delta)

    r_spec_q = pl.BlockSpec((1, 1, block, _STATS_LANES),
                            lambda b, h, i, j, lay, tab: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, scale=scale, block=block,
                          seq_len=seq_len),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nq, nk),
            in_specs=[_q_specs(block, hd), _kv_specs(block, hd),
                      _kv_specs(block, hd), _q_specs(block, hd),
                      r_spec_q, r_spec_q],
            out_specs=_q_specs(block, hd),
            scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(layout_h.arr, jnp.int32), jnp.asarray(table_h.arr, jnp.int32),
      q, k, v, do, lse_b, delta_b)

    # dkv: grid transposed; q-side tensors fetch via the transposed table
    q_spec_t = pl.BlockSpec((1, 1, block, hd),
                            lambda b, h, j, i, lay, tab: (b, h, tab[h, j, i], 0))
    kv_spec_t = pl.BlockSpec((1, 1, block, hd),
                             lambda b, h, j, i, lay, tab: (b, h, j, 0))
    r_spec_t = pl.BlockSpec((1, 1, block, _STATS_LANES),
                            lambda b, h, j, i, lay, tab: (b, h, tab[h, j, i], 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, scale=scale, block=block,
                          seq_len=seq_len),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nk, nq),
            in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t,
                      r_spec_t, r_spec_t],
            out_specs=[kv_spec_t, kv_spec_t],
            scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32),
                            pltpu.VMEM((block, hd), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=_interpret(),
    )(jnp.asarray(layout_t_h.arr, jnp.int32),
      jnp.asarray(table_t_h.arr, jnp.int32),
      q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


_bs_attn.defvjp(_bs_fwd_rule, _bs_bwd_rule)


def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           layout: np.ndarray, block: int,
                           scale: Optional[float] = None,
                           table: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Block-sparse attention over [B, H, S, hd] with a static per-head
    [H, nq, nk] block layout.  Differentiable: backward runs Pallas dq/dkv
    kernels gated by the same layout (cost scales with active blocks).
    Pass a cached ``table`` from :func:`build_fetch_table` to skip the
    O(H·n²) host rebuild per call."""
    B, H, S, hd = q.shape
    layout = np.asarray(layout)
    if layout.ndim == 2:
        layout = layout[None]
    if layout.shape[0] != H:
        assert layout.shape[0] == 1, \
            f"layout heads {layout.shape[0]} != tensor heads {H}"
        layout = np.broadcast_to(layout, (H,) + layout.shape[1:])
    nq, nk = layout.shape[1:]
    assert nq * block >= S and nk * block >= S, (layout.shape, block, S)
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    def pad_to(x, blocks):
        return jnp.pad(x, ((0, 0), (0, 0), (0, blocks * block - S), (0, 0)))

    qp = pad_to(q, nq)
    kp, vp = pad_to(k, nk), pad_to(v, nk)
    # Prepared holders cached by layout CONTENT: the fetch-table builds are
    # O(H·n²) Python loops that must not run per call (the `table` param's
    # whole purpose), and the transposed pair is only consumed by the
    # backward rule.  One content hash per call (C-speed tobytes) replaces
    # four holder constructions + two table rebuilds.
    layout_h = _StaticArr(layout)
    prepared = _PREPARED_CACHE.get(layout_h)
    if prepared is None:
        if table is None:
            table = build_fetch_table(layout)
        elif table.shape[0] != H:
            assert table.shape[0] == 1, table.shape
            table = np.broadcast_to(table, (H,) + table.shape[1:])
        layout_t = np.ascontiguousarray(layout.transpose(0, 2, 1))
        prepared = (_StaticArr(table), _StaticArr(layout_t),
                    _StaticArr(build_fetch_table(layout_t)))
        _PREPARED_CACHE[layout_h] = prepared
    table_h, layout_t_h, table_t_h = prepared
    out = _bs_attn(qp, kp, vp, layout_h, table_h, layout_t_h, table_t_h,
                   block, scale, S)
    return out[:, :, :S]
