"""Block-sparse attention layouts (reference: deepspeed/ops/sparse_attention/
sparsity_config.py — Dense/Fixed/BigBird/Longformer/Variable patterns).

A layout is a [heads, num_blocks, num_blocks] bool array over attention
blocks; the sparse kernel only computes blocks where layout=True.  Pattern
semantics follow the reference classes.
"""
from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def num_layout_heads(self) -> int:
        return self.num_heads if self.different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool)

    def make_layout(self, seq_len: int) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def _broadcast(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global columns (reference Fixed pattern)."""

    def __init__(self, num_heads: int, block: int = 16, num_local_blocks: int = 4,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1, **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        for h in range(self.num_layout_heads()):
            # local windows
            for start in range(0, n, self.num_local_blocks):
                end = min(start + self.num_local_blocks, n)
                layout[h, start:end, start:end] = True
            # global: first num_global_blocks of each window attend/attended
            pattern = h % self.num_different_global_patterns
            for start in range(0, n, self.num_local_blocks):
                g0 = start + pattern * self.num_global_blocks
                g1 = min(g0 + self.num_global_blocks, n)
                layout[h, :, g0:g1] = True        # vertical (everyone → global)
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = True
        if self.attention == "unidirectional":
            tril = np.tril(np.ones((n, n), dtype=bool))
            layout &= tril[None]
        return self._broadcast(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + selected global tokens (reference BSLongformer)."""

    def __init__(self, num_heads: int, block: int = 16, num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads()):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = True
            if self.global_block_end_indices:
                spans = zip(self.global_block_indices, self.global_block_end_indices)
            else:
                spans = [(i, i + 1) for i in self.global_block_indices]
            for g0, g1 in spans:
                layout[h, :, g0:g1] = True
                layout[h, g0:g1, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return self._broadcast(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global blocks (reference BigBird)."""

    def __init__(self, num_heads: int, block: int = 16, num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3, num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0, **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = random.Random(self.seed)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads()):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = True
                for _ in range(self.num_random_blocks):
                    layout[h, i, rng.randrange(n)] = True
            g = self.num_global_blocks
            layout[h, :, :g] = True
            layout[h, :g, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return self._broadcast(layout)


class VariableSparsityConfig(SparsityConfig):
    """Mixed local window sizes + globals (reference Variable)."""

    def __init__(self, num_heads: int, block: int = 16, num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", seed: int = 0, **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = random.Random(self.seed)
        for h in range(self.num_layout_heads()):
            start = 0
            windows = list(self.local_window_blocks)
            while start < n:
                w = windows[0] if len(windows) == 1 else windows.pop(0)
                end = min(start + w, n)
                layout[h, start:end, start:end] = True
                start = end
            for g in self.global_block_indices:
                if g < n:
                    layout[h, :, g] = True
                    layout[h, g, :] = True
            for i in range(n):
                for _ in range(self.num_random_blocks):
                    layout[h, i, rng.randrange(n)] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return self._broadcast(layout)
