"""FP quantizer (reference ⚙: csrc/fp_quantizer/fp_quantize.{cpp,cu} 852 LoC,
bound via deepspeed/ops/fp_quantizer/quantize.py)."""
from .quantize import FP_Quantize, fp_dequantize, fp_quantize

__all__ = ["fp_quantize", "fp_dequantize", "FP_Quantize"]
