"""Groupwise FP8/FP6 quantization (reference ⚙: csrc/fp_quantizer/
fp_quantize.cu — selective_fp_quantize for e4m3/e5m2/fp6, used by ZeRO++
quantized weights and weight-only inference).

TPU-native design: e4m3/e5m2 use REAL fp8 storage (``jnp.float8_e4m3fn`` /
``jnp.float8_e5m2`` are hardware dtypes on TPU — the cast itself is the
quantization kernel, no bit-twiddling needed); per-group f32 scales map each
group's max onto the format's dynamic range.  FP6 (e3m2) has no hardware
dtype, so values are rounded onto the e3m2 grid and stored in int8 words
(value-exact emulation; the wire format stays 1 byte pending a Pallas
bit-packer).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: format → (jnp dtype or None, max representable magnitude)
_FORMATS = {
    "e4m3": (jnp.float8_e4m3fn, 448.0),
    "e5m2": (jnp.float8_e5m2, 57344.0),
    "fp6": (None, 28.0),        # e3m2: max = 2^4 * 1.75
}


def _fp6_round(x):
    """Round f32 onto the e3m2 grid: 2 mantissa bits, exponents 2^-2..2^4
    (subnormals at 2^-2 step 0.0625)."""
    sign = jnp.sign(x)
    mag = jnp.abs(x)
    exp = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(mag, 1e-12))), -2, 4)
    step = jnp.exp2(exp - 2)                       # 4 mantissa steps/octave
    q = jnp.round(mag / step) * step
    return sign * jnp.clip(q, 0.0, 28.0)


def fp_quantize(x: jnp.ndarray, fmt: str = "e4m3",
                group_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x (any shape) → (q [groups, group_size] in the target format,
    scales f32 [groups, 1]).  Pads the tail group with zeros."""
    if fmt not in _FORMATS:
        raise ValueError(f"fmt must be one of {sorted(_FORMATS)}, got {fmt!r}")
    dtype, fmax = _FORMATS[fmt]
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    groups = -(-n // group_size)
    pad = groups * group_size - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    xg = flat.reshape(groups, group_size)
    scale = jnp.max(jnp.abs(xg), axis=1, keepdims=True) / fmax
    scale = jnp.where(scale == 0, 1.0, scale)
    scaled = xg / scale
    if dtype is not None:
        q = scaled.astype(dtype)                   # hardware fp8 cast
    else:
        q = _fp6_round(scaled)                     # e3m2 grid, f32 carrier
    return q, scale


def fp_dequantize(q: jnp.ndarray, scales: jnp.ndarray, shape=None,
                  dtype=jnp.float32) -> jnp.ndarray:
    out = q.astype(jnp.float32) * scales
    flat = out.reshape(-1)
    if shape is not None:
        flat = flat[:int(np.prod(shape))].reshape(shape)
    return flat.astype(dtype)


class FP_Quantize:
    """API-parity wrapper (reference deepspeed/ops/fp_quantizer/quantize.py
    ``FP_Quantize``: quantize(..., q_bits) / dequantize).

    ``return_meta_tensor`` is accepted for signature parity but both paths
    return the same (values, scales) pair — scales ARE the meta tensor here
    (no byte-flattening needed on TPU)."""

    def __init__(self, group_size: int = 512):
        self.group_size = group_size
        self.orig_shape = None

    def quantize(self, x, q_bits: int = 8, stochastic_mode: bool = False,
                 return_meta_tensor: bool = False):
        fmt = {8: "e4m3", 6: "fp6"}.get(q_bits)
        if fmt is None:
            raise NotImplementedError(
                f"q_bits={q_bits} not supported (6=fp6/e3m2, 8=fp8/e4m3); "
                f"the reference's 12-bit path has no TPU dtype yet")
        self.orig_shape = x.shape
        return fp_quantize(x, fmt=fmt, group_size=self.group_size)

    def dequantize(self, q, scale=None, q_bits: int = 8, fp_out=None,
                   shape=None):
        if scale is None:
            raise ValueError("dequantize needs the scales returned by "
                             "quantize (per-group f32 tensor)")
        out_shape = shape if shape is not None else self.orig_shape
        if out_shape is None:
            raise ValueError("pass shape= (no prior quantize call recorded "
                             "the original shape on this instance)")
        return fp_dequantize(q, scale, shape=out_shape)
