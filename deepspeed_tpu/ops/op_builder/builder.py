"""Native op JIT-build framework (reference: op_builder/builder.py —
``OpBuilder`` ABC :117 with sources/include/flags, ``jit_load`` :542 via
torch cpp_extension's versioned cache, compat checks :91; all_ops registry).

TPU flavor: pybind11/torch aren't available, so ops compile with g++ into a
VERSION-KEYED cache (source+flags hash → cache dir) and bind via ctypes.
A source edit produces a new hash → clean rebuild; unchanged sources load
the cached .so with zero compile cost — the reference's version-cache
behavior without torch's extension machinery.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
from typing import Dict, List, Optional, Type

from ...utils.logging import logger

_CACHE_ROOT = os.environ.get(
    "DSTPU_OPS_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilder:
    NAME = "base"

    def sources(self) -> List[str]:
        raise NotImplementedError

    def include_paths(self) -> List[str]:
        return []

    def cxx_flags(self) -> List[str]:
        return ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread"]

    def libraries(self) -> List[str]:
        return []

    # ------------------------------------------------------------------ #
    def is_compatible(self) -> bool:
        """Toolchain probe (reference compat checks :91)."""
        return shutil.which("g++") is not None and \
            all(os.path.exists(s) for s in self.sources())

    def _version_hash(self) -> str:
        h = hashlib.sha256()
        for s in sorted(self.sources()):
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.cxx_flags()).encode())
        h.update(" ".join(self.libraries()).encode())
        return h.hexdigest()[:16]

    def so_path(self) -> str:
        return os.path.join(_CACHE_ROOT, self.NAME, self._version_hash(),
                            f"lib{self.NAME}.so")

    def jit_load(self, verbose: bool = False) -> str:
        """Compile (if this exact source/flag version isn't cached) and
        return the .so path (reference jit_load :542)."""
        if not self.is_compatible():
            raise RuntimeError(f"op {self.NAME!r} is not buildable here "
                               f"(missing g++ or sources)")
        so = self.so_path()
        if os.path.exists(so):
            return so
        os.makedirs(os.path.dirname(so), exist_ok=True)
        cmd = ["g++", *self.cxx_flags(),
               *[f"-I{p}" for p in self.include_paths()],
               *self.sources(), "-o", so,
               *[f"-l{l}" for l in self.libraries()]]
        if verbose:
            logger.info(f"building op {self.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"op {self.NAME} build failed:\n{e.stderr[-2000:]}") from e
        return so

    def load(self):
        """Build + ctypes-bind (subclasses type the symbols)."""
        import ctypes

        return ctypes.CDLL(self.jit_load())


class AsyncIOBuilder(OpBuilder):
    """Reference: op_builder/async_io.py (libaio thread-pool engine)."""
    NAME = "dstpu_aio"

    def sources(self) -> List[str]:
        root = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
        return [os.path.abspath(os.path.join(root, "aio_engine.cpp"))]


#: reference all_ops.py registry
ALL_OPS: Dict[str, Type[OpBuilder]] = {
    AsyncIOBuilder.NAME: AsyncIOBuilder,
}


def get_builder(name: str) -> OpBuilder:
    if name not in ALL_OPS:
        raise KeyError(f"unknown op {name!r}; known: {sorted(ALL_OPS)}")
    return ALL_OPS[name]()
