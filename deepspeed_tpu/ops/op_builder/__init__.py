"""Op builder framework (reference: op_builder/builder.py:117 ``OpBuilder``
ABC + jit_load :542 + all_ops.py registry)."""
from .builder import ALL_OPS, AsyncIOBuilder, OpBuilder, get_builder

__all__ = ["OpBuilder", "AsyncIOBuilder", "ALL_OPS", "get_builder"]
