"""Quantization kernels (reference ⚙: csrc/quantization/{quantize.cu,
quantize_intX.cu, swizzled_quantize.cu, dequantize.cu, fake_quantizer.cu},
bound via deepspeed/ops/quantizer/quantizer.py).

Pallas TPU kernels for groupwise symmetric int8/int4 quantization — the
primitives behind ZeRO++ (qwZ weight allgather, qgZ gradient reduce) and
weight-only inference quantization.  int4 values are packed two-per-int8
(lane-efficient on TPU); scales are f32 per group.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------- #
# int8
# --------------------------------------------------------------------- #
def _quant8_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)                    # [rows, group]
    scale = jnp.max(jnp.abs(x), axis=1, keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q_ref[:] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    s_ref[:] = scale


def quantize_int8(x: jnp.ndarray, group_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x (any shape) → (q int8 [groups, group_size], scales f32 [groups, 1]).

    Flattens; pads the tail group with zeros.
    """
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    groups = -(-n // group_size)
    pad = groups * group_size - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    xg = flat.reshape(groups, group_size)
    block_rows = min(groups, max(8, 4096 // max(group_size // 128, 1)))
    grid = (-(-groups // block_rows),)
    q, s = pl.pallas_call(
        _quant8_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, group_size), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((block_rows, group_size), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((groups, group_size), jnp.int8),
                   jax.ShapeDtypeStruct((groups, 1), jnp.float32)],
        interpret=_interpret(),
    )(xg)
    return q, s


def _dequant8_kernel(q_ref, s_ref, x_ref):
    x_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:]


def dequantize_int8(q: jnp.ndarray, scales: jnp.ndarray, shape=None,
                    dtype=jnp.float32) -> jnp.ndarray:
    groups, group_size = q.shape
    block_rows = min(groups, max(8, 4096 // max(group_size // 128, 1)))
    out = pl.pallas_call(
        _dequant8_kernel,
        grid=(-(-groups // block_rows),),
        in_specs=[pl.BlockSpec((block_rows, group_size), lambda i: (i, 0)),
                  pl.BlockSpec((block_rows, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, group_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((groups, group_size), jnp.float32),
        interpret=_interpret(),
    )(q, scales)
    flat = out.reshape(-1)
    if shape is not None:
        flat = flat[:int(np.prod(shape))].reshape(shape)
    return flat.astype(dtype)


# --------------------------------------------------------------------- #
# int4 (packed pairs in int8 words — swizzled_quantize.cu analogue)
# --------------------------------------------------------------------- #
def quantize_int4(x: jnp.ndarray, group_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (packed int8 [groups, group_size//2], scales [groups, 1])."""
    assert group_size % 2 == 0
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    groups = -(-n // group_size)
    pad = groups * group_size - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    xg = flat.reshape(groups, group_size)
    scale = jnp.max(jnp.abs(xg), axis=1, keepdims=True) / 7.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xg / scale), -7, 7).astype(jnp.int8)
    lo = q[:, 0::2] & 0x0F
    hi = (q[:, 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8), scale


def dequantize_int4(packed: jnp.ndarray, scales: jnp.ndarray, shape=None,
                    dtype=jnp.float32) -> jnp.ndarray:
    lo = (packed << 4).astype(jnp.int8) >> 4       # sign-extend low nibble
    hi = packed >> 4                               # arithmetic shift keeps sign
    groups, half = packed.shape
    q = jnp.zeros((groups, half * 2), jnp.int8)
    q = q.at[:, 0::2].set(lo)
    q = q.at[:, 1::2].set(hi)
    out = q.astype(jnp.float32) * scales
    flat = out.reshape(-1)
    if shape is not None:
        flat = flat[:int(np.prod(shape))].reshape(shape)
    return flat.astype(dtype)


# --------------------------------------------------------------------- #
# Fused wire kernels (EQuARX-style: scale + quantize + nibble-pack in ONE
# Pallas kernel so the collective's operand is produced directly as wire
# bytes — no intermediate full-precision materialization between the
# quantize and the exchange, and no separate jnp-level pack pass that XLA
# won't fuse on TPU).  int4 uses a HALF-SPLIT pack (element i pairs with
# i + group_size/2) instead of the even/odd interleave above: contiguous
# lane slices lower cleanly in Mosaic where a stride-2 lane gather does
# not.  Pack∘unpack is the identity either way, so dequantized VALUES are
# bit-identical to the unfused path; only the wire byte layout differs.
# --------------------------------------------------------------------- #
def wire_width(bits: int, group_size: int) -> int:
    """Wire bytes per group (int8: one byte per value; int4: two values
    per byte)."""
    return group_size if bits == 8 else group_size // 2


def _quant_pack8_kernel(x_ref, w_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=1, keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    w_ref[:] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    s_ref[:] = scale


def _quant_pack4_kernel(x_ref, w_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=1, keepdims=True) / 7.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -7, 7).astype(jnp.int8)
    half = q.shape[1] // 2
    lo = q[:, :half] & 0x0F
    hi = (q[:, half:] & 0x0F) << 4
    w_ref[:] = (lo | hi).astype(jnp.int8)
    s_ref[:] = scale


def _unpack_wire(w: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Wire bytes [rows, W] → int8 values [rows, group_size] (half-split
    layout for int4; identity for int8)."""
    if bits == 8:
        return w
    lo = (w << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
    hi = w >> 4                                  # arithmetic shift keeps sign
    return jnp.concatenate([lo, hi], axis=1)


def _block_rows(groups: int, group_size: int) -> int:
    return min(groups, max(8, 4096 // max(group_size // 128, 1)))


def quant_pack_wire(x: jnp.ndarray, bits: int,
                    group_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x (any shape) → (wire int8 [groups, wire_width], scales f32
    [groups, 1]) in ONE kernel.  Flattens; pads the tail group with zeros.
    Scale/round math is identical to :func:`quantize_int8` /
    :func:`quantize_int4`, so dequantized values round-trip bit-identically
    to the unfused pair."""
    assert bits in (4, 8), bits
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    groups = -(-n // group_size)
    pad = groups * group_size - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    xg = flat.reshape(groups, group_size)
    W = wire_width(bits, group_size)
    block_rows = _block_rows(groups, group_size)
    kernel = _quant_pack8_kernel if bits == 8 else _quant_pack4_kernel
    return pl.pallas_call(
        kernel,
        grid=(-(-groups // block_rows),),
        in_specs=[pl.BlockSpec((block_rows, group_size), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((block_rows, W), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((groups, W), jnp.int8),
                   jax.ShapeDtypeStruct((groups, 1), jnp.float32)],
        interpret=_interpret(),
    )(xg)


def unpack_dequant_wire(w: jnp.ndarray, scales: jnp.ndarray, bits: int,
                        shape=None, dtype=jnp.float32) -> jnp.ndarray:
    """(wire [groups, W], scales [groups, 1]) → values, unpack + dequant in
    one kernel.  Inverse of :func:`quant_pack_wire`."""
    assert bits in (4, 8), bits
    groups, W = w.shape
    group_size = W if bits == 8 else W * 2

    def kernel(w_ref, s_ref, out_ref):
        out_ref[:] = _unpack_wire(w_ref[:], bits).astype(jnp.float32) * s_ref[:]

    out = pl.pallas_call(
        kernel,
        grid=(-(-groups // _block_rows(groups, group_size)),),
        in_specs=[pl.BlockSpec((_block_rows(groups, group_size), W),
                               lambda i: (i, 0)),
                  pl.BlockSpec((_block_rows(groups, group_size), 1),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_block_rows(groups, group_size), group_size),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((groups, group_size), jnp.float32),
        interpret=_interpret(),
    )(w, scales)
    flat = out.reshape(-1)
    if shape is not None:
        flat = flat[:int(np.prod(shape))].reshape(shape)
    return flat.astype(dtype)


def unpack_dequant_mean(w: jnp.ndarray, scales: jnp.ndarray, bits: int,
                        n: int) -> jnp.ndarray:
    """Fused unpack + dequant + mean over the peer axis: (wire
    [n, groups, W], scales [n, groups, 1]) → f32 [groups * group_size].

    This is the receive side of a quantized reduce-scatter — each of the
    ``n`` peers contributed a quantized copy of MY partition; one kernel
    dequantizes and mean-reduces them without materializing the n
    full-precision copies in HBM.  The reduction is ``sum(axis=0) / n``,
    the same lax reduction ``jnp.mean`` lowers to, so the result is
    bit-identical to dequantize-then-``jnp.mean``."""
    assert bits in (4, 8), bits
    n_, groups, W = w.shape
    assert n_ == n, (n_, n)
    group_size = W if bits == 8 else W * 2
    block_rows = _block_rows(groups, group_size)

    def kernel(w_ref, s_ref, out_ref):
        wv = w_ref[:]                              # [n, rows, W]
        rows = wv.shape[1]
        vals = _unpack_wire(wv.reshape(n * rows, W), bits).astype(jnp.float32)
        vals = vals * s_ref[:].reshape(n * rows, 1)
        out_ref[:] = jnp.sum(vals.reshape(n, rows, group_size), axis=0) / n

    out = pl.pallas_call(
        kernel,
        grid=(-(-groups // block_rows),),
        in_specs=[pl.BlockSpec((n, block_rows, W), lambda i: (0, i, 0)),
                  pl.BlockSpec((n, block_rows, 1), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((block_rows, group_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((groups, group_size), jnp.float32),
        interpret=_interpret(),
    )(w, scales)
    return out.reshape(-1)


def get_quant_fns(bits: int):
    """(quantize, dequantize) pair for a bit width — the ONE dispatch table
    (used by ZeRO++ comm, weight-only serving, and the Quantizer class)."""
    if bits == 4:
        return quantize_int4, dequantize_int4
    if bits == 8:
        return quantize_int8, dequantize_int8
    raise ValueError(f"bits must be 4 or 8, got {bits}")


class Quantizer:
    """Reference binding-class shape (deepspeed/ops/quantizer/quantizer.py)."""

    def __init__(self, q_bits: int = 8, group_size: int = 256):
        assert q_bits in (4, 8)
        self.q_bits = q_bits
        self.group_size = group_size

    def quantize(self, x):
        return get_quant_fns(self.q_bits)[0](x, self.group_size)

    def dequantize(self, q, scales, shape=None, dtype=jnp.float32):
        return get_quant_fns(self.q_bits)[1](q, scales, shape, dtype)
