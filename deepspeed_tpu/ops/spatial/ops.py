"""Diffusers spatial ops, TPU-native (reference ⚙: csrc/spatial/ — 298 LoC
of CUDA fused bias/activation ops for UNet blocks).

On TPU these are XLA-fusable expressions: NHWC is the native convolution
layout, bias+activation fuse into the producing matmul/conv epilogue, and
GroupNorm lowers to a handful of fused reductions — the hand-written CUDA
fusion buys nothing here, so these are thin, well-tested math definitions
matching the reference ops' signatures.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def bias_add(x: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """[N, H, W, C] + [C] (reference nhwc_bias_add)."""
    return x + bias


def bias_add_add(x: jnp.ndarray, bias: jnp.ndarray,
                 other: jnp.ndarray) -> jnp.ndarray:
    """x + bias + other (reference nhwc_bias_add_add — residual variant)."""
    return x + bias + other


def bias_geglu(x: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """GEGLU used by diffusers FeedForward: split the (biased) channel dim,
    gate with gelu (reference gated activation kernels)."""
    y = x + bias
    a, b = jnp.split(y, 2, axis=-1)
    return a * jax.nn.gelu(b)


def group_norm(x: jnp.ndarray, num_groups: int, scale: jnp.ndarray,
               bias: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """GroupNorm over [N, H, W, C] (diffusers ResnetBlock norm)."""
    N, H, W, C = x.shape
    g = x.reshape(N, H, W, num_groups, C // num_groups).astype(jnp.float32)
    mu = jnp.mean(g, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(g, axis=(1, 2, 4), keepdims=True)
    out = (g - mu) * jax.lax.rsqrt(var + eps)
    return (out.reshape(N, H, W, C) * scale + bias).astype(x.dtype)


def nhwc_conv(x: jnp.ndarray, kernel: jnp.ndarray, stride: int = 1,
              padding: str = "SAME") -> jnp.ndarray:
    """NHWC conv with HWIO kernel — TPU's native layout (the reference
    transposes NCHW↔NHWC around its kernels; here there's nothing to
    transpose)."""
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
