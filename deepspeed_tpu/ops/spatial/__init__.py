"""Spatial / diffusers inference ops (reference ⚙: csrc/spatial/ — fused
NHWC bias-add variants used by the diffusers UNet/VAE wrappers, bound via
op_builder/spatial_inference.py)."""
from .ops import bias_add, bias_add_add, bias_geglu, group_norm, nhwc_conv

__all__ = ["bias_add", "bias_add_add", "bias_geglu", "group_norm",
           "nhwc_conv"]
