"""Pallas fused Adam/AdamW (reference ⚙: csrc/adam/multi_tensor_adam.cu +
fused_adam_frontend.cpp, bound via deepspeed/ops/adam/fused_adam.py).

The CUDA kernel's win is one pass over HBM updating param/m/v together; the
Pallas kernel does the same on TPU: each grid step streams one VMEM block of
(p, g, m, v), computes the update in f32, and writes all three outputs —
4 reads + 3 writes per element, no intermediate HBM round-trips.  Exposed both
as a raw kernel and as an optax ``GradientTransformation`` (``fused_adam``)
so it drops into the engine's optimizer factory.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 1024 * 128  # elements per grid step (512KB f32 per buffer)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tile_plan(shape):
    """Shared (rows, width, flat2d, unflat, spec, grid) tiling for the
    streaming optimizer kernels — ONE copy of the flatten-to-(rows, 128)
    scaffolding used by adam/lion/adagrad (and ops/lamb)."""
    n = int(np.prod(shape)) if shape else 1
    width = 128
    rows = -(-n // width)
    pad = rows * width - n

    def flat2d(x):
        f = x.reshape(-1).astype(jnp.float32)
        if pad:
            f = jnp.pad(f, (0, pad))
        return f.reshape(rows, width)

    def unflat(x):
        return x.reshape(-1)[:n].reshape(shape)

    block_rows = max(min(rows, BLOCK // width), 8)
    spec = pl.BlockSpec((block_rows, width), lambda i: (i, 0))
    grid = (-(-rows // block_rows),)
    return rows, width, flat2d, unflat, spec, grid


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, bc1_ref, bc2_ref, lr_ref,
                 p_out, m_out, v_out,
                 *, beta1, beta2, eps, weight_decay, adam_w_mode):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    bc1 = bc1_ref[0, 0]
    bc2 = bc2_ref[0, 0]
    # lr rides an SMEM operand like bc1/bc2: under the engine's jitted step
    # it's a TRACED schedule value — a closure constant would fail lowering
    lr = lr_ref[0, 0]

    if weight_decay and not adam_w_mode:
        g = g + weight_decay * p
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    m_hat = m_new / bc1
    v_hat = v_new / bc2
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if weight_decay and adam_w_mode:
        update = update + weight_decay * p
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = m_new.astype(m_out.dtype)
    v_out[:] = v_new.astype(v_out.dtype)


def fused_adam_update(p, g, m, v, step, lr=1e-3, beta1=0.9, beta2=0.999,
                      eps=1e-8, weight_decay=0.0, adam_w_mode=True,
                      bias_correction=True):
    """Single-array fused Adam step; returns (p', m', v')."""
    shape, dtype = p.shape, p.dtype
    rows, width, flat2d, unflat, spec, grid = _tile_plan(shape)
    pf, gf, mf, vf = map(flat2d, (p, g, m, v))
    t = step.astype(jnp.float32) + 1.0
    bc1 = (1.0 - beta1 ** t if bias_correction else jnp.float32(1.0)).reshape(1, 1)
    bc2 = (1.0 - beta2 ** t if bias_correction else jnp.float32(1.0)).reshape(1, 1)
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1, 1)

    kernel = functools.partial(
        _adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode)
    p2, m2, v2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, width), jnp.float32)] * 3,
        interpret=_interpret(),
    )(pf, gf, mf, vf, bc1, bc2, lr_arr)

    return unflat(p2).astype(dtype), unflat(m2), unflat(v2)


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def optax_wrap(per_leaf_update, state_cls, num_moments: int,
               learning_rate) -> optax.GradientTransformation:
    """Shared optax wrapper for fused kernels that compute NEW PARAMS
    in-kernel: flattens the tree, applies ``per_leaf_update(lr, count, p, g,
    *moments) -> (new_p, *new_moments)`` per leaf, and returns additive
    updates (new_p - p) to stay optax-conformant.  Used by
    fused_adam/fused_lion here and fused_lamb (ops/lamb)."""

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        moments = [jax.tree.map(zeros, params) for _ in range(num_moments)]
        return state_cls(jnp.zeros((), jnp.int32), *moments)

    def update(grads, state, params=None):
        assert params is not None, "fused optimizers require params"
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_moments = [treedef.flatten_up_to(state[i + 1])
                        for i in range(num_moments)]
        outs = [per_leaf_update(lr, state.count, p, g, *ms)
                for p, g, *ms in zip(flat_p, flat_g, *flat_moments)]
        new_params = treedef.unflatten([o[0] for o in outs])
        new_moments = [treedef.unflatten([o[i + 1] for o in outs])
                       for i in range(num_moments)]
        updates = jax.tree.map(lambda n, o: n - o, new_params, params)
        return updates, state_cls(state.count + 1, *new_moments)

    return optax.GradientTransformation(init, update)


def fused_adam(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
               weight_decay=0.0, adam_w_mode=True,
               bias_correction=True) -> optax.GradientTransformation:
    """Optax-compatible fused Adam (additive updates = new_p - p)."""
    def leaf(lr, count, p, g, m, v):
        return fused_adam_update(p, g, m, v, count, lr=lr, beta1=b1, beta2=b2,
                                 eps=eps, weight_decay=weight_decay,
                                 adam_w_mode=adam_w_mode,
                                 bias_correction=bias_correction)

    return optax_wrap(leaf, FusedAdamState, 2, learning_rate)


# ------------------------------------------------------------------ #
# Lion (reference ⚙: csrc/lion/, deepspeed/ops/lion/)
# ------------------------------------------------------------------ #
def _lion_kernel(p_ref, g_ref, m_ref, lr_ref, p_out, m_out,
                 *, beta1, beta2, weight_decay):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    lr = lr_ref[0, 0]
    update = jnp.sign(beta1 * m + (1.0 - beta1) * g) + weight_decay * p
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = (beta2 * m + (1.0 - beta2) * g).astype(m_out.dtype)


def fused_lion_update(p, g, m, lr=1e-4, beta1=0.9, beta2=0.99, weight_decay=0.0):
    shape, dtype = p.shape, p.dtype
    rows, width, flat2d, unflat, spec, grid = _tile_plan(shape)
    pf, gf, mf = map(flat2d, (p, g, m))
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    p2, m2 = pl.pallas_call(
        functools.partial(_lion_kernel, beta1=beta1, beta2=beta2,
                          weight_decay=weight_decay),
        grid=grid,
        in_specs=[spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, width), jnp.float32)] * 2,
        interpret=_interpret(),
    )(pf, gf, mf, lr_arr)
    return unflat(p2).astype(dtype), unflat(m2)


# ------------------------------------------------------------------ #
# Adagrad (reference ⚙: csrc/adagrad/cpu_adagrad.cpp)
# ------------------------------------------------------------------ #
def _adagrad_kernel(p_ref, g_ref, a_ref, lr_ref, p_out, a_out,
                    *, eps, weight_decay):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    a = a_ref[:].astype(jnp.float32)
    lr = lr_ref[0, 0]
    if weight_decay:
        g = g + weight_decay * p
    a_new = a + g * g
    p_out[:] = (p - lr * g / (jnp.sqrt(a_new) + eps)).astype(p_out.dtype)
    a_out[:] = a_new


def fused_adagrad_update(p, g, a, lr=1e-2, eps=1e-10, weight_decay=0.0):
    """Single-array fused Adagrad step → (p', accumulator')."""
    shape, dtype = p.shape, p.dtype
    rows, width, flat2d, unflat, spec, grid = _tile_plan(shape)
    pf, gf, af = map(flat2d, (p, g, a))
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    p2, a2 = pl.pallas_call(
        functools.partial(_adagrad_kernel, eps=eps, weight_decay=weight_decay),
        grid=grid,
        in_specs=[spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, width), jnp.float32)] * 2,
        interpret=_interpret(),
    )(pf, gf, af, lr_arr)
    return unflat(p2).astype(dtype), unflat(a2)


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    acc: Any


def fused_adagrad(learning_rate=1e-2, eps=1e-10,
                  weight_decay=0.0) -> optax.GradientTransformation:
    """Optax-compatible fused Adagrad (reference ops/adagrad)."""
    def leaf(lr, count, p, g, a):
        return fused_adagrad_update(p, g, a, lr=lr, eps=eps,
                                    weight_decay=weight_decay)

    return optax_wrap(leaf, FusedAdagradState, 1, learning_rate)


class FusedLionState(NamedTuple):
    count: jnp.ndarray
    mu: Any


def fused_lion(learning_rate=1e-4, b1=0.9, b2=0.99,
               weight_decay=0.0) -> optax.GradientTransformation:
    """Optax-compatible fused Lion (reference deepspeed/ops/lion)."""
    def leaf(lr, count, p, g, m):
        return fused_lion_update(p, g, m, lr=lr, beta1=b1, beta2=b2,
                                 weight_decay=weight_decay)

    return optax_wrap(leaf, FusedLionState, 1, learning_rate)
