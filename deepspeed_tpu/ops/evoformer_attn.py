"""Evoformer attention (DS4Science parity).

Reference ⚙: ``csrc/deepspeed4science/evoformer_attn/`` (14.9k LoC
CUDA/CUTLASS fwd/bwd) exposed via ``deepspeed.ops.deepspeed4science``.

The op: MSA/triangle attention over 5-D tensors [batch, n_seq, seq_len,
heads, dim] with up to two additive biases (mask bias broadcast over rows,
pair bias shared across the n_seq dim).  On TPU the memory win of the CUDA
kernel (never materializing [*, H, S, S] for long S) is obtained by chunking
the query dimension with online softmax — same structure as our flash kernel,
expressed with lax.scan so XLA fuses the bias additions in.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp


def _dense_attention(q, k, v, biases):
    """Naive path for short sequences. q/k/v: [B, N, S, H, D]."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bnqhd,bnkhd->bnhqk", q, k) * scale
    for b in biases:
        scores = scores + b
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v)


def evoformer_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        biases: Optional[List[Optional[jnp.ndarray]]] = None,
                        chunk_size: int = 256) -> jnp.ndarray:
    """DS4Science EvoformerAttention-compatible op.

    q/k/v: [batch, n_seq, seq_len, heads, head_dim]
    biases: up to two, broadcastable to [batch, n_seq, heads, S_q, S_k]
            (mask bias typically [B, N, 1, 1, S], pair bias [B, 1, H, S, S]).
    """
    biases = [b for b in (biases or []) if b is not None]
    B, N, S, H, D = q.shape
    if S <= chunk_size:
        return _dense_attention(q, k, v, biases)

    assert S % chunk_size == 0, "pad seq_len to a chunk multiple"
    n = S // chunk_size
    scale = 1.0 / math.sqrt(D)
    qc = q.reshape(B, N, n, chunk_size, H, D)

    def q_chunk(ci):
        qi = jax.lax.dynamic_index_in_dim(qc, ci, 2, keepdims=False)  # [B,N,c,H,D]
        scores = jnp.einsum("bnqhd,bnkhd->bnhqk", qi, k) * scale      # [B,N,H,c,S]
        for b in biases:
            bb = jnp.broadcast_to(b, (B, N, H, S, S)) if b.shape[-2] == S else None
            if bb is not None:
                bslice = jax.lax.dynamic_slice_in_dim(bb, ci * chunk_size,
                                                      chunk_size, axis=3)
                scores = scores + bslice
            else:
                scores = scores + b  # bias constant over q dim (mask bias)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v)

    outs = jax.lax.map(q_chunk, jnp.arange(n))           # [n,B,N,c,H,D]
    return outs.transpose(1, 2, 0, 3, 4, 5).reshape(B, N, S, H, D)


class EvoformerAttention:
    """Reference module name (op_builder/evoformer_attn.py binding)."""

    def __init__(self, chunk_size: int = 256):
        self.chunk_size = chunk_size

    def __call__(self, q, k, v, biases=None):
        return evoformer_attention(q, k, v, biases, self.chunk_size)


# DS4Science-compatible alias
DS4Sci_EvoformerAttention = evoformer_attention
