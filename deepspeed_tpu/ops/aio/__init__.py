"""Python binding for the native async-IO engine.

Reference analogues: ``op_builder/async_io.py`` (JIT build) +
``deepspeed/ops/aio`` (binding).  pybind11 is not in this image, so the build
is a direct g++ shared-object compile (cached by source mtime) bound with
ctypes — the op_builder JIT-load pattern, TPU-host flavored.
"""
from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None


def _build() -> str:
    """Version-cached build via the op_builder framework (hash-keyed cache;
    a source edit rebuilds cleanly, unchanged sources load instantly)."""
    from ..op_builder import AsyncIOBuilder

    return AsyncIOBuilder().jit_load()


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(_build())
        lib.dstpu_aio_create.restype = ctypes.c_void_p
        lib.dstpu_aio_create.argtypes = [ctypes.c_int, ctypes.c_int64]
        lib.dstpu_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_open.restype = ctypes.c_int
        lib.dstpu_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dstpu_aio_close.argtypes = [ctypes.c_int]
        for fn in (lib.dstpu_aio_pwrite, lib.dstpu_aio_pread):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
        for fn in (lib.dstpu_aio_wait, lib.dstpu_aio_poll):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        _LIB = lib
    return _LIB


class AsyncIOHandle:
    """Reference analogue: deepspeed_py_aio_handle.cpp handle object."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 thread_count: int = 4, single_submit: bool = False,
                 overlap_events: bool = True):
        self._lib = _lib()
        self._h = self._lib.dstpu_aio_create(int(thread_count), int(block_size))
        self.block_size = block_size
        self.thread_count = thread_count

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.dstpu_aio_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # ---------------------------------------------------------------- #
    def async_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> "AioRequest":
        arr = np.ascontiguousarray(array)
        fd = self._lib.dstpu_aio_open(path.encode(), 1)
        if fd < 0:
            raise OSError(f"cannot open {path} for write")
        rid = self._lib.dstpu_aio_pwrite(
            self._h, fd, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, offset)
        return AioRequest(self, rid, fd, keepalive=arr)

    def async_pread(self, array: np.ndarray, path: str, offset: int = 0) -> "AioRequest":
        assert array.flags["C_CONTIGUOUS"], "read target must be contiguous"
        fd = self._lib.dstpu_aio_open(path.encode(), 0)
        if fd < 0:
            raise OSError(f"cannot open {path} for read")
        rid = self._lib.dstpu_aio_pread(
            self._h, fd, array.ctypes.data_as(ctypes.c_void_p), array.nbytes, offset)
        return AioRequest(self, rid, fd, keepalive=array)

    def sync_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        return self.async_pwrite(array, path, offset).wait()

    def sync_pread(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        return self.async_pread(array, path, offset).wait()


class AioRequest:
    def __init__(self, handle: AsyncIOHandle, rid: int, fd: int, keepalive=None):
        self.handle = handle
        self.rid = rid
        self.fd = fd
        self._keepalive = keepalive  # keep buffer alive until completion
        self._done = False

    def wait(self) -> int:
        if self._done:
            return 0
        status = self.handle._lib.dstpu_aio_wait(self.handle._h, self.rid)
        self.handle._lib.dstpu_aio_close(self.fd)
        self._done = True
        self._keepalive = None
        if status != 0:
            raise OSError(f"aio request failed with errno {-status}")
        return 0

    def poll(self) -> bool:
        if self._done:
            return True
        return bool(self.handle._lib.dstpu_aio_poll(self.handle._h, self.rid))


def aio_available() -> bool:
    try:
        _lib()
        return True
    except Exception:
        return False
