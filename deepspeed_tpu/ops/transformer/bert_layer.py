"""BERT-era fused transformer training layer (reference ⚙:
csrc/transformer/ 12.9k LoC CUDA — ds_transformer_cuda.cpp + gelu/dropout/
normalize/softmax kernels — bound as ``DeepSpeedTransformerLayer``,
deepspeed/ops/transformer/transformer.py:296).

TPU stance: the hand-fused CUDA encoder layer exists to beat torch's op
dispatch; under XLA one traced layer IS one fused program, so this module
provides the same config surface + layer semantics (pre/post-LN, bias
dropout residual, bidirectional attention with mask) executing on the
framework's attention path.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ...models.families import layer_norm


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Reference config fields (transformer.py:40)."""
    batch_size: int = 1
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    fp16: bool = False
    stochastic_mode: bool = False


class DeepSpeedTransformerLayer:
    """One BERT encoder layer with the reference's param surface."""

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config

    def init_params(self, key: jax.Array, dtype=jnp.float32) -> Dict:
        c = self.config
        D, F = c.hidden_size, c.intermediate_size
        ks = jax.random.split(key, 6)
        dense = lambda k, shape: (jax.random.normal(k, shape) *
                                  c.initializer_range).astype(dtype)
        ln = lambda: {"scale": jnp.ones((D,), dtype),
                      "bias": jnp.zeros((D,), dtype)}
        return {
            "qkv": {"kernel": dense(ks[0], (D, 3 * D)),
                    "bias": jnp.zeros((3 * D,), dtype)},
            "attn_out": {"kernel": dense(ks[1], (D, D)),
                         "bias": jnp.zeros((D,), dtype)},
            "attn_ln": ln(),
            "fc1": {"kernel": dense(ks[2], (D, F)),
                    "bias": jnp.zeros((F,), dtype)},
            "fc2": {"kernel": dense(ks[3], (F, D)),
                    "bias": jnp.zeros((D,), dtype)},
            "out_ln": ln(),
        }

    def __call__(self, params: Dict, x: jnp.ndarray,
                 attention_mask: Optional[jnp.ndarray] = None,
                 rng: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jnp.ndarray:
        c = self.config
        B, S, D = x.shape
        H = c.heads
        hd = D // H
        eps = c.layer_norm_eps

        def dropout(h, r, ratio):
            if deterministic or ratio == 0 or r is None:
                return h
            keep = 1.0 - ratio
            mask = jax.random.bernoulli(r, keep, h.shape)
            return jnp.where(mask, h / keep, 0)

        r1 = r2 = r3 = None
        if rng is not None:
            r1, r2, r3 = jax.random.split(rng, 3)

        h_in = layer_norm(x, params["attn_ln"]["scale"], params["attn_ln"]["bias"], eps) if c.pre_layer_norm else x
        qkv = h_in @ params["qkv"]["kernel"] + params["qkv"]["bias"]
        q, k, v = jnp.split(qkv.reshape(B, S, 3, H, hd), 3, axis=2)
        q, k, v = (t[:, :, 0] for t in (q, k, v))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / math.sqrt(hd)
        if attention_mask is not None:
            scores = scores + jnp.where(
                attention_mask[:, None, None, :].astype(bool), 0.0, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        probs = dropout(probs, r3, c.attn_dropout_ratio)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, D)
        attn = dropout(o @ params["attn_out"]["kernel"] +
                       params["attn_out"]["bias"], r1, c.hidden_dropout_ratio)
        x = x + attn
        if not c.pre_layer_norm:
            x = layer_norm(x, params["attn_ln"]["scale"], params["attn_ln"]["bias"], eps)

        h_in = layer_norm(x, params["out_ln"]["scale"], params["out_ln"]["bias"], eps) if c.pre_layer_norm else x
        h = jax.nn.gelu(h_in @ params["fc1"]["kernel"] + params["fc1"]["bias"])
        mlp = dropout(h @ params["fc2"]["kernel"] + params["fc2"]["bias"], r2,
                      c.hidden_dropout_ratio)
        x = x + mlp
        if not c.pre_layer_norm:
            x = layer_norm(x, params["out_ln"]["scale"], params["out_ln"]["bias"], eps)
        return x
