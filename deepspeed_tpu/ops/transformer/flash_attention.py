"""Pallas TPU flash attention (fwd + bwd), the framework's hot attention op.

Reference analogues: the CUDA inference/training attention kernels
(``csrc/transformer/inference/csrc/softmax.cu``, evoformer/cutlass attention
``csrc/deepspeed4science/evoformer_attn``, FastGen ``blocked_flash``).  This is
the TPU equivalent: blocked online-softmax attention tiled for the MXU, with a
recompute-based backward (dq and dkv kernels), exposed through
``jax.custom_vjp`` so it drops into any autodiff'd model.

Layout: inputs [B, S, H, hd] (GQA allowed: KV heads = H // group).  The kernel
operates per (batch, head, q-block) with kv-blocks as the innermost grid dim,
accumulating in VMEM scratch (f32).  Causal masking skips fully-masked blocks.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30
#: Row-stats arrays (lse, delta) carry a trailing lane dim so their block
#: shape satisfies Mosaic's (sublane, lane) tiling rule — a rank-3 [B, H, S]
#: block of (1, 1, block_q) fails lowering on real TPUs.  8 lanes (== the
#: array dim, which Mosaic accepts) keeps the residual 16x smaller than the
#: canonical 128-lane layout.
_STATS_LANES = 8


def _interpret() -> bool:
    """Pallas TPU kernels run in interpreter mode on non-TPU backends
    (CPU-simulated meshes in tests)."""
    return jax.default_backend() != "tpu"


def _cdiv(a, b):
    return (a + b - 1) // b


def _causal_kv_index(causal: bool, block_q: int, block_k: int):
    """KV index map with the causal DMA skip: fully-masked kv blocks clamp
    to the last needed one, so Pallas skips the copy (unchanged block
    between consecutive grid steps).  Grid order (b, h, iq, ik)."""
    if not causal:
        return lambda b, h, i, j: (b, h, j, 0)

    def index(b, h, i, j):
        needed_last = ((i + 1) * block_q - 1) // block_k
        return (b, h, jnp.minimum(j, needed_last), 0)

    return index


def _causal_q_index(causal: bool, block_q: int, block_k: int):
    """Q-side index map for the dkv grid (b, h, ik, iq): below-diagonal q
    blocks clamp UP to the first needed one (same DMA-skip trick)."""
    if not causal:
        return lambda b, h, j, i: (b, h, i, 0)

    def index(b, h, j, i):
        first_needed = (j * block_k) // block_q
        i_eff = jnp.maximum(i, first_needed)
        return (b, h, i_eff, 0)

    return index


# ===================================================================== #
# Forward kernel
# ===================================================================== #
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
                scale, causal, block_q, block_k, seq_len):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_first = iq * block_q
    k_first = ik * block_k
    # Causal: block fully above the diagonal contributes nothing.
    needed = jnp.logical_or(not causal, q_first + block_q - 1 >= k_first)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [BQ, hd]
        k = k_ref[0, 0].astype(jnp.float32)            # [BK, hd]
        v = v_ref[0, 0].astype(jnp.float32)            # [BK, hd]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        q_pos = q_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = k_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                           # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                 # rescale factor
        p = jnp.exp(s - m_new)                          # [BQ, BK]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l_safe),
                                         lse_ref.shape[2:])


def _fwd(q, k, v, scale, causal, block_q, block_k):
    B, H, S, hd = q.shape
    nq, nk = _cdiv(S, block_q), _cdiv(S, block_k)
    Sq, Sk = nq * block_q, nk * block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, Sq - S), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, Sk - S), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, Sk - S), (0, 0)))

    # Causal DMA skip (VERDICT round-1 weak #3): compute for masked blocks
    # is pl.when-gated; the clamped index maps remove their DMA too.
    kv_index = _causal_kv_index(causal, block_q, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=S)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, _STATS_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    return out[:, :, :S], lse[:, :, :S, 0]


# ===================================================================== #
# Backward kernels
# ===================================================================== #
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, seq_len):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_first = iq * block_q
    k_first = ik * block_k
    needed = jnp.logical_or(not causal, q_first + block_q - 1 >= k_first)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        q_pos = q_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = k_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _write():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, block_q, block_k, seq_len):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_first = iq * block_q
    k_first = ik * block_k
    needed = jnp.logical_or(not causal, q_first + block_q - 1 >= k_first)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        q_pos = q_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = k_first + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    do = g
    B, H, S, hd = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,S]

    nq, nk = _cdiv(S, block_q), _cdiv(S, block_k)
    Sq, Sk = nq * block_q, nk * block_k
    pad_q = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, Sq - S), (0, 0)))
    pad_k = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, Sk - S), (0, 0)))
    qp, kp, vp, dop = pad_q(q), pad_k(k), pad_k(v), pad_q(do)
    pad_r = lambda x: jnp.broadcast_to(
        jnp.pad(x, ((0, 0), (0, 0), (0, Sq - S)))[..., None],
        (B, H, Sq, _STATS_LANES))
    lsep = pad_r(lse)
    deltap = pad_r(delta)

    q_spec = pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, hd),
                          _causal_kv_index(causal, block_q, block_k))
    r_spec = pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                          lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B, H, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    # dkv: kv-blocks outer, q-blocks inner; below-diagonal q blocks are the
    # masked ones here, so the q index map clamps UP to the first needed one
    q_spec2 = pl.BlockSpec((1, 1, block_q, hd),
                           _causal_q_index(causal, block_q, block_k))
    k_spec2 = pl.BlockSpec((1, 1, block_k, hd), lambda b, h, j, i: (b, h, j, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                           _causal_q_index(causal, block_q, block_k))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B, H, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sk, hd), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Sk, hd), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, hd), jnp.float32),
                        pltpu.VMEM((block_k, hd), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :, :S], dk[:, :, :S], dv[:, :, :S]


# ===================================================================== #
# Public API
# ===================================================================== #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K):
    """Flash attention over [B, S, H, hd] inputs (GQA: kv may have fewer heads).

    Returns [B, S, H, hd].  Falls back to padded head_dim for hd < 128 lanes
    (Mosaic handles sub-128 minor dims; hd is kept as-is).
    """
    B, S, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        assert H % KV == 0, "query heads must be a multiple of kv heads"
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    # Clamp block sizes to the sequence, rounded UP to a lane-aligned
    # multiple of 128 (padding handles S not divisible by the block); a
    # non-128-multiple minor dim fails Mosaic lowering on real TPUs.
    align = lambda x: ((x + 127) // 128) * 128
    bq = min(block_q, align(max(128, S)))
    bk = min(block_k, align(max(128, S)))
    # [B,S,H,hd] -> [B,H,S,hd]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bhsd(qt, kt, vt, scale, causal, bq, bk)
    return out.transpose(0, 2, 1, 3)
