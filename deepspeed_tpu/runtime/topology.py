"""Device-mesh topology: the TPU-native replacement for DeepSpeed process groups.

Reference analogues:
  - ``deepspeed/utils/groups.py:53-707`` (DP/TP/EP/SP group construction)
  - ``deepspeed/runtime/pipe/topology.py:12,244,251`` (ProcessTopology /
    PipeModelDataParallelTopology / PipelineParallelGrid)

Instead of building torch.distributed process groups, we build a single
``jax.sharding.Mesh`` with named axes.  Every "group" in DeepSpeed maps to a
mesh axis (or a tuple of axes) here; XLA collectives over a named axis are the
group collectives.

Axis semantics (sizes multiply to the device count):

  ====== ===========================================================
  pipe   pipeline-parallel stages (PipelineModule)
  data   pure data parallel / ZeRO partitioning ("dp")
  expert expert-parallel sub-axis of data parallelism (MoE ``ep_size``)
  seq    Ulysses/ring sequence parallelism ("sp")
  tensor tensor (model) parallelism ("tp"/"mp")
  ====== ===========================================================

Group mapping (DeepSpeed name -> mesh axes):

  data_parallel_group          -> ("data", "expert")   # batch sharding axes
  expert_parallel_group        -> ("expert",)
  expert_data_parallel_group   -> ("data",)
  sequence_parallel_group      -> ("seq",)
  tensor_parallel_group        -> ("tensor",)
  pipe_parallel_group          -> ("pipe",)
  model_parallel_group         -> ("pipe", "tensor")
  zero_partition_group         -> ("data", "expert", "seq")  # ZeRO shards over full DP×SP

Axis order is chosen for ICI locality: "tensor" innermost (fastest-varying
device index, shortest links), "pipe" outermost.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

PIPE = "pipe"
DATA_OUTER = "data_outer"  # MiCS replica groups (size dp/zero_shard_size)
DATA = "data"
EXPERT = "expert"
SEQ = "seq"
TENSOR = "tensor"

#: Canonical outer→inner axis order of every mesh built here.
AXIS_ORDER: Tuple[str, ...] = (PIPE, DATA_OUTER, DATA, EXPERT, SEQ, TENSOR)

#: DeepSpeed group name → mesh axes.
GROUP_AXES: Dict[str, Tuple[str, ...]] = {
    "data_parallel": (DATA_OUTER, DATA, EXPERT),
    "expert_parallel": (EXPERT,),
    "expert_data_parallel": (DATA_OUTER, DATA),
    "sequence_parallel": (SEQ,),
    "sequence_data_parallel": (DATA_OUTER, DATA, EXPERT, SEQ),
    "tensor_parallel": (TENSOR,),
    "model_parallel": (PIPE, TENSOR),
    "pipe_parallel": (PIPE,),
    #: ZeRO shards over the INNER data axes only; with zero_shard_size set
    #: (MiCS, runtime/zero/mics.py:64) the outer axis replicates shards and
    #: gradient allreduce spans it (allreduce_mics_shard_grads :432).
    "zero_partition": (DATA, EXPERT, SEQ),
    "zero_replica": (DATA_OUTER,),
    "world": AXIS_ORDER,
}


class ProcessTopology:
    """Named-axes cartesian rank grid (reference: runtime/pipe/topology.py:12).

    Pure-python coordinate bookkeeping over flat rank ids; used by the pipeline
    partitioner, checkpoint naming, and the launcher.  ``axes`` is outer→inner.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = tuple(axes)
        self.dims = tuple(int(d) for d in dims)
        self._strides = []
        stride = 1
        for d in reversed(self.dims):
            self._strides.append(stride)
            stride *= d
        self._strides = list(reversed(self._strides))

    def world_size(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_rank(self, **coords: int) -> int:
        if set(coords) != set(self.axes):
            raise ValueError(f"need all coords {self.axes}, got {tuple(coords)}")
        return sum(coords[a] * s for a, s in zip(self.axes, self._strides))

    def get_coord(self, rank: int):
        coord = {}
        for axis, stride, dim in zip(self.axes, self._strides, self.dims):
            coord[axis] = (rank // stride) % dim
        return dataclasses.make_dataclass("Coord", coord.keys())(**coord)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Lists of ranks that differ only along ``axis`` (a "process group")."""
        if axis not in self.axes:
            return []
        others = [a for a in self.axes if a != axis]
        lists = []
        for combo in np.ndindex(*[self.get_dim(a) for a in others]):
            fixed = dict(zip(others, (int(c) for c in combo)))
            ranks = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs: int) -> List[int]:
        out = []
        for rank in range(self.world_size()):
            coord = self.get_coord(rank)
            if all(getattr(coord, k) == v for k, v in filter_kwargs.items()):
                out.append(rank)
        return out

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def __repr__(self) -> str:  # pragma: no cover
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pipe×model(tensor)×data grid (reference: runtime/pipe/topology.py:244)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=[PIPE, DATA, TENSOR], dims=[num_pp, num_dp, num_mp])


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Parallelism degrees; sizes not given default to 1, data absorbs the rest.

    ``zero_shard_size`` (MiCS ``mics_shard_size`` / hpZ partition size): caps
    the ZeRO shard group — the data dimension splits into
    (data_outer × data) with data = zero_shard_size; shards replicate across
    data_outer.
    """

    pipe: int = 1
    data: int = -1  # -1: infer from device count
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    zero_shard_size: int = -1  # -1: shard over the full data extent

    def resolve(self, n_devices: int) -> Dict[str, int]:
        dims = {PIPE: self.pipe, DATA_OUTER: 1, DATA: self.data,
                EXPERT: self.expert, SEQ: self.seq, TENSOR: self.tensor}
        fixed = int(np.prod([d for k, d in dims.items() if d > 0 and k != DATA]))
        if self.data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by pipe*expert*seq*tensor={fixed}")
            dims[DATA] = n_devices // fixed
        if self.zero_shard_size > 0:
            if dims[DATA] % self.zero_shard_size != 0:
                raise ValueError(
                    f"data dim {dims[DATA]} not divisible by zero_shard_size "
                    f"{self.zero_shard_size}")
            dims[DATA_OUTER] = dims[DATA] // self.zero_shard_size
            dims[DATA] = self.zero_shard_size
        total = int(np.prod(list(dims.values())))
        if total != n_devices:
            raise ValueError(f"mesh dims {dims} product {total} != device count {n_devices}")
        return dims


class MeshTopology:
    """Owns the global ``jax.sharding.Mesh`` and group-name → axis resolution.

    This is the object the engine, ZeRO shardings, MoE, Ulysses, and the
    pipeline engine all consult.  One instance per training job.
    """

    def __init__(
        self,
        config: Optional[TopologyConfig] = None,
        devices: Optional[Sequence[Any]] = None,
        axis_types: Optional[Dict[str, Any]] = None,
    ):
        import jax
        from jax.sharding import Mesh

        self.config = config or TopologyConfig()
        if devices is None:
            devices = jax.devices()
        self.dims = self.config.resolve(len(devices))
        shape = tuple(self.dims[a] for a in AXIS_ORDER)
        device_grid = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(device_grid, AXIS_ORDER)
        self.process_topology = ProcessTopology(AXIS_ORDER, shape)

    # -------------------------------------------------------------- #
    # Group resolution (deepspeed.utils.groups equivalents)
    # -------------------------------------------------------------- #
    def axes_for(self, group: str) -> Tuple[str, ...]:
        if group not in GROUP_AXES:
            raise KeyError(f"unknown group {group!r}; known: {sorted(GROUP_AXES)}")
        return GROUP_AXES[group]

    def group_size(self, group: str) -> int:
        return int(np.prod([self.dims[a] for a in self.axes_for(group)]))

    # Named accessors mirroring deepspeed/utils/groups.py
    def get_data_parallel_world_size(self) -> int:
        return self.group_size("data_parallel")

    def get_sequence_parallel_world_size(self) -> int:
        return self.group_size("sequence_parallel")

    def get_tensor_parallel_world_size(self) -> int:
        return self.group_size("tensor_parallel")

    def get_expert_parallel_world_size(self) -> int:
        return self.group_size("expert_parallel")

    def get_pipe_parallel_world_size(self) -> int:
        return self.group_size("pipe_parallel")

    def world_size(self) -> int:
        return self.mesh.size

    # -------------------------------------------------------------- #
    # Sharding helpers
    # -------------------------------------------------------------- #
    def named_sharding(self, *spec: Any):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def batch_spec(self):
        """PartitionSpec for a [batch, seq, ...] input array."""
        from jax.sharding import PartitionSpec

        batch_axes = tuple(a for a in (DATA_OUTER, DATA, EXPERT)
                           if self.dims[a] > 1) or (DATA,)
        seq_axis = SEQ if self.dims[SEQ] > 1 else None
        return PartitionSpec(batch_axes, seq_axis)

    def zero_axes(self) -> Tuple[str, ...]:
        """Axes over which ZeRO partitions params/grads/optimizer state."""
        return tuple(a for a in self.axes_for("zero_partition") if self.dims[a] > 1)

    # -------------------------------------------------------------- #
    # Slice model (ICI vs DCN) — hierarchical collectives
    # -------------------------------------------------------------- #
    def set_cross_slice_axes(self, axes: Optional[Sequence[str]]) -> None:
        """Explicit override of which mesh axes cross a slice (DCN)
        boundary — for the CPU sim and tests, or when the config says so
        (``overlap.cross_slice_axes``).  ``None`` restores derivation."""
        if axes is not None:
            bad = sorted(set(axes) - set(AXIS_ORDER))
            if bad:
                raise ValueError(f"unknown mesh axes {bad}; "
                                 f"known: {list(AXIS_ORDER)}")
            axes = tuple(a for a in AXIS_ORDER if a in set(axes))
        self._cross_slice_override = axes

    def cross_slice_axes(self) -> Tuple[str, ...]:
        """Mesh axes whose neighbors live in a DIFFERENT TPU slice — hops
        along these cross DCN, not ICI (the slow domain of the 2-hop
        hierarchical collectives in ``runtime/comm/hierarchical.py``).

        Resolution order: :meth:`set_cross_slice_axes` override →
        ``DSTPU_CROSS_SLICE_AXES`` env (comma list; how the CPU sim and the
        comm_sweep bench model a multislice job) → derived from each
        device's ``slice_index`` (multislice TPU runtimes expose it; absent
        or uniform → single slice, no cross axes).  Only nontrivial axes
        are ever returned."""
        import os

        override = getattr(self, "_cross_slice_override", None)
        if override is None:
            env = os.environ.get("DSTPU_CROSS_SLICE_AXES", "").strip()
            if env:
                override = tuple(a.strip() for a in env.split(",")
                                 if a.strip())
                bad = sorted(set(override) - set(AXIS_ORDER))
                if bad:
                    raise ValueError(
                        f"DSTPU_CROSS_SLICE_AXES names unknown axes {bad}; "
                        f"known: {list(AXIS_ORDER)}")
        if override is not None:
            return tuple(a for a in AXIS_ORDER
                         if a in set(override) and self.dims[a] > 1)
        return self._derived_cross_slice_axes()

    def _derived_cross_slice_axes(self) -> Tuple[str, ...]:
        grid = np.asarray(self.mesh.devices)
        slice_ids = np.asarray(
            [getattr(d, "slice_index", None) for d in grid.ravel()],
            dtype=object).reshape(grid.shape)
        if all(s is None for s in slice_ids.ravel()) or \
                len({s for s in slice_ids.ravel()}) <= 1:
            return ()
        out = []
        for k, axis in enumerate(AXIS_ORDER):
            if self.dims[axis] <= 1:
                continue
            first = np.take(slice_ids, 0, axis=k)
            if any((np.take(slice_ids, i, axis=k) != first).any()
                   for i in range(1, grid.shape[k])):
                out.append(axis)
        return tuple(out)

    def slice_axes(self) -> Tuple[str, ...]:
        """Nontrivial mesh axes fully inside one slice (all hops ride
        ICI)."""
        cross = set(self.cross_slice_axes())
        return tuple(a for a in AXIS_ORDER
                     if self.dims[a] > 1 and a not in cross)

    def __repr__(self) -> str:  # pragma: no cover
        return f"MeshTopology({self.dims})"


def compat_shard_map(f, mesh, in_specs, out_specs, manual_axes=None):
    """``shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` with partial-manual ``axis_names``
    and ``check_vma``; 0.4.x only has ``jax.experimental.shard_map`` where
    the same partial-manual region is spelled as the complement set
    (``auto=``) and the varying-manual check is ``check_rep``.  One seam so
    every sharded step builder keeps working on both (``manual_axes=None``
    = fully manual).
    """
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        if manual_axes is not None:
            kwargs["axis_names"] = set(manual_axes)
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if manual_axes is not None:
        auto = frozenset(mesh.axis_names) - set(manual_axes)
        # 0.4.x's auto= support miscompiles some partial-manual programs
        # when an Auto axis is nontrivial (observed: XLA hard-abort on the
        # quantized-wire step under tensor parallelism).  A process abort
        # mid-suite is far worse than a clean refusal, so degrade exactly
        # the unreliable combination.
        try:
            sizes = dict(getattr(mesh, "shape", {}) or {})
        except TypeError:
            sizes = {}
        live_auto = sorted(a for a in auto if int(sizes.get(a, 1)) > 1)
        if live_auto:
            raise NotImplementedError(
                f"partial-manual shard_map with nontrivial Auto axes "
                f"{live_auto} needs jax.shard_map (newer jax); this jax's "
                f"experimental shard_map miscompiles that combination — "
                f"use the fused path on model-parallel meshes")
    mapped = _shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False, auto=auto)
    # 0.4.x partial-manual shard_map has no eager impl (NotImplementedError
    # outside jit); wrapping is a no-op for callers already under jit
    return jax.jit(mapped) if auto else mapped


def shard_map_context(topo: "MeshTopology"):
    """(mesh, already_manual_axes) for building a possibly-nested shard_map.

    Inside an enclosing partial-manual region (e.g. the explicit-comm train
    step, manual over the data axes) jax requires nested shard_maps to be
    built against the *context* abstract mesh — its axis_types record which
    axes are already Manual — and to name only still-Auto axes.  At top
    level the concrete mesh is the right thing.
    """
    import jax

    try:
        manual_t = jax.sharding.AxisType.Manual
        am = jax.sharding.get_abstract_mesh()
        types = getattr(am, "axis_types", None)
        if types is not None and any(t == manual_t for t in types):
            already = {n for n, t in zip(am.axis_names, types)
                       if t == manual_t}
            return am, already
    except Exception:  # noqa: BLE001 - introspection is best-effort
        pass
    return topo.mesh, set()


def mesh_shape_str(dims: Dict[str, int]) -> str:
    """Mesh dims -> compact ``axis:size`` string (``data:4,tensor:2``) —
    the wire format of ``DSTPU_ELASTIC_MESH_SHAPE``.  Trivial axes are
    elided; an all-trivial mesh renders its world size on ``data``.  A
    MiCS mesh (``data_outer`` > 1) renders as the FULL data extent plus
    ``zero_shard:<inner>``, mirroring how :class:`TopologyConfig` spells
    it (``zero_shard_size``), so the string parses back losslessly."""
    data_outer = int(dims.get(DATA_OUTER, 1))
    parts = []
    for a, n in dims.items():
        n = int(n)
        if a == DATA_OUTER or a not in AXIS_ORDER or n <= 1:
            continue
        if a == DATA and data_outer > 1:
            parts.append(f"{DATA}:{n * data_outer}")
            parts.append(f"zero_shard:{n}")
        else:
            parts.append(f"{a}:{n}")
    if data_outer > 1 and not any(p.startswith(f"{DATA}:") for p in parts):
        # outer replication over a trivial inner data axis
        parts.insert(0, f"zero_shard:{int(dims.get(DATA, 1))}")
        parts.insert(0, f"{DATA}:{data_outer * int(dims.get(DATA, 1))}")
    if not parts:
        total = int(np.prod([int(n) for n in dims.values()])) if dims else 1
        parts = [f"{DATA}:{total}"]
    return ",".join(parts)


def parse_mesh_shape(text: str) -> TopologyConfig:
    """``data:4,tensor:2`` (or a bare world size ``8``) -> TopologyConfig.

    The inverse of :func:`mesh_shape_str`; how a restarted worker turns the
    elastic agent's re-planned shape into its mesh."""
    text = (text or "").strip()
    if not text:
        raise ValueError("empty mesh shape")
    if text.isdigit():
        return TopologyConfig(data=int(text))
    field_by_axis = {PIPE: "pipe", DATA: "data", EXPERT: "expert",
                     SEQ: "seq", TENSOR: "tensor",
                     "zero_shard": "zero_shard_size"}
    kw: Dict[str, int] = {}
    for part in text.split(","):
        axis, _, size = part.partition(":")
        axis = axis.strip()
        if axis not in field_by_axis:
            raise ValueError(f"unknown mesh axis {axis!r} in {text!r}; "
                             f"known: {sorted(field_by_axis)}")
        kw[field_by_axis[axis]] = int(size)
    if "data" not in kw:
        kw["data"] = -1   # absorb the remaining devices, as usual
    return TopologyConfig(**kw)


def topology_config_from_env() -> Optional[TopologyConfig]:
    """The elastic agent's re-planned mesh, if this worker was restarted
    with ``--allow-reshape`` onto different capacity (None otherwise)."""
    import os

    text = os.environ.get("DSTPU_ELASTIC_MESH_SHAPE")
    return parse_mesh_shape(text) if text else None


_TOPOLOGY: Optional[MeshTopology] = None


def initialize_mesh(
    config: Optional[TopologyConfig] = None,
    devices: Optional[Sequence[Any]] = None,
    force: bool = False,
) -> MeshTopology:
    """Create (or return) the global mesh topology.

    Reference analogue: ``deepspeed.utils.groups.initialize`` +
    ``comm/comm.py:609 initialize_mesh_device``.
    """
    global _TOPOLOGY
    if _TOPOLOGY is None or force:
        _TOPOLOGY = MeshTopology(config, devices)
    return _TOPOLOGY


def get_topology() -> MeshTopology:
    if _TOPOLOGY is None:
        return initialize_mesh()
    return _TOPOLOGY


def reset_topology() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None
