"""ZeRO stage semantics as sharding rules.

Reference analogues: ``runtime/zero/stage_1_and_2.py`` (optimizer/grad
partitioning), ``runtime/zero/stage3.py`` + ``partition_parameters.py``
(parameter partitioning with gather-on-use).

On TPU, ZeRO is not a hand-written partition/gather engine: each stage is a
*sharding assignment* over the mesh's ZeRO axes, and XLA inserts the
allgather/reduce-scatter collectives plus prefetch/overlap scheduling that the
reference implements manually (stage3 prefetching, overlap_comm side streams).

  stage 0: params R, grads R (psum), opt R            — plain DP
  stage 1: params R, grads R, opt SHARDED             — optimizer partitioning
  stage 2: params R, grads SHARDED (reduce-scatter), opt SHARDED
  stage 3: params SHARDED (allgather-on-use), grads SHARDED, opt SHARDED

``param_persistence_threshold`` maps directly: params smaller than the
threshold stay replicated ("persistent" in the reference's sense —
stage3.py:214 persistence filtering) since gathering tiny arrays costs more
latency than the memory saved.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..topology import MeshTopology


def _spec_axes(spec: Optional[PartitionSpec]) -> set:
    used = set()
    if spec is None:
        return used
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def shard_param_spec(
    shape: Tuple[int, ...],
    zero_axes: Tuple[str, ...],
    zero_size: int,
    base_spec: Optional[PartitionSpec] = None,
) -> PartitionSpec:
    """Assign ``zero_axes`` to the best dimension of an array.

    ``base_spec`` carries pre-existing model-parallel sharding (e.g. a TP axis
    on a Megatron-style Linear); ZeRO axes are added on a *different* dim.
    Picks the largest dim divisible by ``zero_size``; returns ``base_spec``
    unchanged (replicated over ZeRO axes) if none divides.
    """
    if zero_size <= 1 or not zero_axes:
        return base_spec if base_spec is not None else PartitionSpec()
    ndim = len(shape)
    base = list(base_spec) if base_spec is not None else []
    base = base + [None] * (ndim - len(base))
    taken = _spec_axes(base_spec)
    if any(a in taken for a in zero_axes):
        return PartitionSpec(*base)  # already sharded over zero axes

    candidates = [d for d in range(ndim)
                  if base[d] is None and shape[d] % zero_size == 0]
    if not candidates:
        return PartitionSpec(*base)
    dim = max(candidates, key=lambda d: shape[d])
    base[dim] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return PartitionSpec(*base)


class ZeroShardingPlan:
    """Per-stage sharding assignment for params / grads / optimizer state."""

    def __init__(self, topology: MeshTopology, stage: int,
                 param_persistence_threshold: int = 100_000,
                 base_specs: Any = None):
        self.topology = topology
        self.stage = int(stage)
        self.threshold = int(param_persistence_threshold)
        self.zero_axes = topology.zero_axes()
        self.zero_size = int(np.prod([topology.dims[a] for a in self.zero_axes])) \
            if self.zero_axes else 1
        self.base_specs = base_specs

    # -------------------------------------------------------------- #
    def _base_spec_for(self, path) -> Optional[PartitionSpec]:
        if self.base_specs is None:
            return None
        node = self.base_specs
        try:
            for key in path:
                k = getattr(key, "key", getattr(key, "idx", None))
                node = node[k]
            return node if isinstance(node, PartitionSpec) else None
        except (KeyError, IndexError, TypeError):
            return None

    def _sharded_spec(self, path, leaf) -> PartitionSpec:
        shape = tuple(leaf.shape)
        base = self._base_spec_for(path)
        size = int(np.prod(shape)) if shape else 1
        if size < self.threshold or not shape:
            return base if base is not None else PartitionSpec()
        return shard_param_spec(shape, self.zero_axes, self.zero_size, base)

    def _replicated_spec(self, path, leaf) -> PartitionSpec:
        base = self._base_spec_for(path)
        return base if base is not None else PartitionSpec()

    # -------------------------------------------------------------- #
    def param_specs(self, params: Any) -> Any:
        """PartitionSpec pytree for model parameters (persistent storage)."""
        fn = self._sharded_spec if self.stage >= 3 else self._replicated_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    def grad_specs(self, params: Any) -> Any:
        """Sharding constraint applied to grads inside the train step."""
        fn = self._sharded_spec if self.stage >= 2 else self._replicated_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    def opt_state_specs_for_param(self, params: Any) -> Any:
        """Spec pytree used for optimizer moments (same layout as params)."""
        fn = self._sharded_spec if self.stage >= 1 else self._replicated_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    # -------------------------------------------------------------- #
    def grad_bytes(self, params: Any) -> float:
        """fp32 gradient wire volume of one accumulation boundary (the
        overlap auto-tuner's bucket-sizing input: grads are exchanged in
        fp32 regardless of compute dtype).  Per-leaf sizing is shared with
        the bucket planner so the two can never disagree."""
        from ..overlap.bucketing import leaf_bytes

        return float(sum(leaf_bytes(leaf)
                         for leaf in jax.tree.leaves(params)))

    def prefetch_shard_dim(self, path, leaf) -> Optional[int]:
        """Which dim of a stage-3 param carries the ZeRO axes (None when
        replicated/persistent) — the gather dimension the weight-prefetch
        machinery (``runtime/overlap/prefetch.py``) rebuilds a full layer
        group along."""
        spec = self._sharded_spec(path, leaf)
        zset = set(self.zero_axes)
        for d, entry in enumerate(spec):
            entries = entry if isinstance(entry, (tuple, list)) else (entry,)
            if any(a in zset for a in entries if a is not None):
                return d
        return None

    # -------------------------------------------------------------- #
    def param_shardings(self, params: Any) -> Any:
        mesh = self.topology.mesh
        return jax.tree.map(lambda s: NamedSharding(mesh, s), self.param_specs(params),
                            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def opt_state_shardings(self, opt_state: Any, params: Any) -> Any:
        """Match optimizer-state leaves to their parameter's sharding.

        Optax states mirror the param pytree inside each moment container
        (mu/nu/trace/… have the params' exact tree structure), so the mapping
        is structural: any opt-state subtree whose treedef equals the param
        treedef gets the moment spec tree leaf-for-leaf.  Shape-keyed lookup
        would mis-place state when two params share a shape but carry
        different base/TP specs (e.g. D==F collides gate_proj/down_proj).
        Leaves outside param-shaped subtrees (step counts, scalars) stay
        replicated.
        """
        mesh = self.topology.mesh
        spec_tree = self.opt_state_specs_for_param(params)
        param_struct = jax.tree_util.tree_structure(params)
        sharding_tree = jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        replicated = NamedSharding(mesh, PartitionSpec())

        param_leaves = jax.tree.leaves(params)

        def mirrors_params(node) -> bool:
            """Same treedef AND same leaf shapes: a scalar-leaf tree with the
            param structure (e.g. onebit-LAMB trust coefficients) must stay
            replicated, not inherit moment specs."""
            if jax.tree_util.tree_structure(node) != param_struct:
                return False
            return all(getattr(l, "shape", None) == p.shape
                       for l, p in zip(jax.tree.leaves(node), param_leaves))

        def assign(node):
            if mirrors_params(node):
                return sharding_tree
            return jax.tree.map(lambda _: replicated, node)

        return jax.tree.map(assign, opt_state, is_leaf=mirrors_params)
