"""Twin-Flow fractional optimizer-state offload (Offload++).

Reference: ``deepspeed/runtime/zero/offload_config.py`` (``ratio``) and
``blogs/deepspeed-offloadpp/README.md`` — partial optimizer offload where a
``ratio`` fraction of the state lives on the host and the rest stays in
device HBM, so the optimizer step overlaps a small host stream with the
device-resident update instead of paying the full PCIe round trip.

TPU design: every optimizer-state leaf is split along dim 0 —
``[:n_dev]`` stays in HBM, ``[n_dev:]`` is placed in ``pinned_host`` memory.
The wrapped optimizer joins the two halves inside the jitted step (XLA turns
the host→HBM placement change into a DMA it can overlap with compute),
runs the inner optax update on the joined state, and splits the result back.
No separate host-optimizer kernel is needed — the "CPU Adam" of the
reference is replaced by XLA host streaming (SURVEY §2: cpu-Adam analogue).

The split index is rounded to the leaf's dim-0 shard count so both halves
keep the ZeRO sharding layout; scalars and 1-row leaves stay fully on
device (they are bytes-irrelevant).
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class TwinFlowState(NamedTuple):
    """dev/host trees have the inner state's treedef; each leaf is the
    leading/trailing dim-0 slice of the corresponding inner leaf (possibly
    0 rows)."""

    dev: Any
    host: Any


@dataclasses.dataclass(frozen=True)
class _LeafPlan:
    axis: int           # split axis (the leaf's largest dim)
    n_dev: int          # rows of `axis` staying in HBM
    n_host: int         # rows of `axis` on pinned host
    scalar: bool        # shape () — never split


def _axis_shards(sharding, axis: int) -> int:
    spec = getattr(sharding, "spec", None)
    if not spec or len(spec) <= axis or spec[axis] is None:
        return 1
    entries = spec[axis] if isinstance(spec[axis], (tuple, list)) \
        else (spec[axis],)
    n = 1
    for a in entries:
        n *= sharding.mesh.shape[a]
    return n


def _plan_leaf(shape: Tuple[int, ...], ratio: float, sharding) -> _LeafPlan:
    if not shape:
        return _LeafPlan(0, 0, 0, True)
    # Split along the LARGEST dim: stacked-layer moments carry a tiny
    # leading [num_layers] axis where a dim-0 split can only hit multiples
    # of 1/L — the widest axis gives the finest approximation of ratio.
    axis = max(range(len(shape)), key=lambda d: shape[d])
    rows = shape[axis]
    granule = _axis_shards(sharding, axis)  # halves stay shard-divisible
    n_host = int(round(rows * ratio / granule)) * granule
    # keep BOTH halves non-empty: a 0-row dev half would reintroduce the
    # zero-size-leaf problem (orbax refuses them) the host placeholder
    # avoids — at ratio→1 rounding may otherwise consume the whole leaf
    n_host = min(max(n_host, 0), rows - granule)
    if n_host <= 0 or (rows - n_host) % granule:
        n_host = 0  # cannot split cleanly; keep on device
    return _LeafPlan(axis, rows - n_host, n_host, False)


def build_twin_flow(inner: optax.GradientTransformation, ratio: float,
                    params: Any, plan, mesh):
    """Wrap ``inner`` with fractional host offload.

    Returns ``(optimizer, init_shardings, byte_split)``: the wrapped
    transformation (state = TwinFlowState), the matching sharding pytree for
    ``jax.jit(optimizer.init, out_shardings=...)``, and a
    ``() -> (device_bytes, host_bytes)`` accounting fn.
    """
    on_tpu = jax.default_backend() == "tpu"
    inner_shapes = jax.eval_shape(inner.init, params)
    inner_shardings = plan.opt_state_shardings(inner_shapes, params)

    flat_shapes, treedef = jax.tree_util.tree_flatten(inner_shapes)
    flat_shardings = treedef.flatten_up_to(inner_shardings)
    leaf_plans = tuple(
        _plan_leaf(tuple(s.shape), ratio, sh)
        for s, sh in zip(flat_shapes, flat_shardings))

    def _host(sharding):
        if not on_tpu:
            return sharding  # CPU backend has no pinned_host memory space
        try:
            return sharding.with_memory_kind("pinned_host")
        except Exception:  # noqa: BLE001
            return sharding

    def _dev(sharding):
        if not on_tpu:
            return sharding
        try:
            return sharding.with_memory_kind("device")
        except Exception:  # noqa: BLE001
            return sharding

    def split(full_tree):
        """Inner state → TwinFlowState (host halves re-placed per step)."""
        flat = treedef.flatten_up_to(full_tree)
        dev, host = [], []
        for leaf, lp, sh in zip(flat, leaf_plans, flat_shardings):
            if lp.scalar or lp.n_host == 0:
                dev.append(leaf)
                # scalar placeholder, not a 0-size array (orbax refuses to
                # serialize zero-size leaves); join() keys off lp.n_host
                host.append(jnp.zeros((), jnp.result_type(leaf)))
                continue
            d = jax.lax.slice_in_dim(leaf, 0, lp.n_dev, axis=lp.axis)
            h = jax.lax.slice_in_dim(leaf, lp.n_dev, lp.n_dev + lp.n_host,
                                     axis=lp.axis)
            if on_tpu:
                h = jax.device_put(h, _host(sh))
            dev.append(d)
            host.append(h)
        return TwinFlowState(dev=treedef.unflatten(dev),
                             host=treedef.unflatten(host))

    def join(state: TwinFlowState):
        """TwinFlowState → inner state, host halves streamed to HBM."""
        dflat = treedef.flatten_up_to(state.dev)
        hflat = treedef.flatten_up_to(state.host)
        full = []
        for d, h, lp, sh in zip(dflat, hflat, leaf_plans, flat_shardings):
            if lp.scalar or lp.n_host == 0:
                full.append(d)
                continue
            if on_tpu:
                h = jax.device_put(h, _dev(sh))
            full.append(jnp.concatenate([d, h], axis=lp.axis))
        return treedef.unflatten(full)

    def init(p):
        return split(inner.init(p))

    def update(grads, state: TwinFlowState, p=None):
        updates, new_inner = inner.update(grads, join(state), p)
        return updates, split(new_inner)

    def init_shardings():
        from jax.sharding import NamedSharding, PartitionSpec

        replicated = NamedSharding(mesh, PartitionSpec())
        dev_sh, host_sh = [], []
        for sh, lp in zip(flat_shardings, leaf_plans):
            dev_sh.append(sh)
            # scalar placeholders (unsplit leaves) must be replicated — a
            # sharded spec on a 0-d array is ill-formed
            host_sh.append(_host(sh) if lp.n_host else replicated)
        return TwinFlowState(dev=treedef.unflatten(dev_sh),
                             host=treedef.unflatten(host_sh))

    def byte_split():
        """(device_bytes, host_bytes) of the planned placement — for tests
        and the memory estimator."""
        dev_b = host_b = 0
        for s, lp in zip(flat_shapes, leaf_plans):
            if lp.scalar:
                dev_b += s.dtype.itemsize
                continue
            row = s.dtype.itemsize
            for d, n in enumerate(s.shape):
                if d != lp.axis:
                    row *= n
            dev_b += lp.n_dev * row
            host_b += lp.n_host * row
        return dev_b, host_b

    return optax.GradientTransformation(init, update), init_shardings(), \
        byte_split
