"""ZeRO configuration (reference analogue: deepspeed/runtime/zero/config.py:86).

The knob set matches the reference where meaningful on TPU.  Stage semantics:

  * stage 0 — replicated params/grads/optimizer state (plain DP; grads psum).
  * stage 1 — optimizer state sharded over the ZeRO axes.
  * stage 2 — + gradients reduce-scattered (sharded) over the ZeRO axes.
  * stage 3 — + parameters sharded (FSDP): XLA inserts allgather-on-use and
    the latency-hiding scheduler provides the prefetch/overlap the reference
    implements by hand (stage3.py:1294, partitioned_param_coordinator.py:285).

Knobs that configure hand-rolled CUDA machinery with no XLA equivalent
(bucket sizes for the Python-driven allreduce loop) are accepted for config
compatibility and used as hints where applicable.
"""
from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    #: Fraction of optimizer-state BYTES offloaded to pinned host memory
    #: (Twin-Flow / Offload++ ``ratio``, reference offload_config.py +
    #: blogs/deepspeed-offloadpp).  1.0 = everything offloaded (classic
    #: ZeRO-Offload); 0 < ratio < 1 splits each state leaf along dim 0 —
    #: the leading (1-ratio) stays in HBM, the trailing ratio streams from
    #: host at step time.
    ratio: float = 1.0


class ZeroStageEnum(int, Enum):
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload: Optional[bool] = None  # deprecated alias

    # Stage-3 knobs (reference zero/config.py:208-310)
    prefetch_bucket_size: int = Field(50_000_000, ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(2**63 - 1, ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")

    # ZeRO++ (reference zero/config.py:294-326)
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    #: LoCo error feedback on the quantized gradient wire (reference
    #: coalesced_collectives.py:81 loco variant)
    zeropp_loco: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    mics_shard_size: int = Field(-1)
    mics_hierarchical_params_gather: bool = False

    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    override_module_apply: bool = True
    log_trace_cache_warnings: bool = False

    def offload_optimizer_device(self) -> str:
        if self.cpu_offload:
            return "cpu"
        return self.offload_optimizer.device.value if self.offload_optimizer else "none"

    def offload_param_device(self) -> str:
        return self.offload_param.device.value if self.offload_param else "none"
