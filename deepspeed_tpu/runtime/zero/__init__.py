"""ZeRO public API (reference: deepspeed.zero — Init :824, GatheredParameters
:2121 in runtime/zero/partition_parameters.py; MiCS_Init runtime/zero/mics.py:64).

On TPU the reference's parameter-stub machinery is unnecessary: ``Init`` is a
context that makes model init produce *already-sharded* params (jit with
out_shardings, so each device only ever materializes its shard), and
``GatheredParameters`` temporarily re-places shards as replicated arrays.
"""
from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax

from .config import DeepSpeedZeroConfig
from .sharding import ZeroShardingPlan, shard_param_spec


class Init:
    """Shard-on-init context (reference zero.Init, partition_parameters.py:824).

    Usage::

        with zero.Init(topology=topo) as zi:
            params = zi.materialize(lambda: model.init_params(key))

    ``materialize`` compiles the init fn with sharded out_shardings, so no
    device ever holds the full parameter set — the property the reference
    achieves by converting params to partitioned stubs at construction.
    """

    def __init__(self, module=None, topology=None, config_dict_or_path=None,
                 zero_stage: int = 3, param_persistence_threshold: int = 100_000,
                 dtype=None, enabled: bool = True, mpu=None, **kw):
        from ..topology import get_topology

        self.topology = topology or get_topology()
        self.enabled = enabled
        self.plan = ZeroShardingPlan(
            self.topology, zero_stage,
            param_persistence_threshold=param_persistence_threshold)
        self.dtype = dtype

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def materialize(self, init_fn, *args) -> Any:
        if not self.enabled:
            return init_fn(*args)
        shapes = jax.eval_shape(init_fn, *args)
        shardings = self.plan.param_shardings(shapes)
        out = jax.jit(init_fn, out_shardings=shardings)(*args)
        if self.dtype is not None:
            out = jax.tree.map(lambda x: x.astype(self.dtype), out)
        return out


class MiCS_Init(Init):
    """Reference: runtime/zero/mics.py:64 — ZeRO-3 sharded within sub-groups,
    replicated across (build the mesh with ``zero_shard_size``)."""

    def __init__(self, *args, mics_shard_size: int = -1, **kw):
        if mics_shard_size > 0:
            from ..topology import TopologyConfig, initialize_mesh

            kw["topology"] = initialize_mesh(
                TopologyConfig(zero_shard_size=mics_shard_size), force=True)
        super().__init__(*args, **kw)


@contextlib.contextmanager
def GatheredParameters(params: Any, modifier_rank: Optional[int] = None,
                       fwd_module=None, enabled: bool = True):
    """Temporarily materialize full (replicated) values of sharded params
    (reference ctx :2121).  Yields the gathered pytree; mutations do NOT
    propagate back (functional params — reassign explicitly)."""
    if not enabled:
        yield params
        return
    from ..topology import get_topology

    topo = get_topology()
    gathered = jax.device_put(
        params, jax.tree.map(lambda _: topo.replicated(), params))
    yield gathered


def unwrap_model_for_generation(model):
    return model
