"""Host-DRAM page tier: the staging layer between HBM and (later) NVMe.

This is the TPU analogue of DeepSpeed's ``swap_tensor`` host buffers
(``AsyncPartitionedParameterSwapper``'s pinned buffer pool): a bounded,
LRU-evicting dictionary of canonical-row page payloads living in host
memory, fed by double-buffered D2H transfers and drained by H2D copies at
resume time.  Two consumers share it:

* serving — :class:`~deepspeed_tpu.inference.v2.ragged.kv_swap.KVSwapManager`
  parks preempted sequences' cold KV pages (and spilled radix-prefix pages)
  here so resume is an H2D copy + page-table patch instead of a prefill
  recompute;
* training — :class:`HostOffloadPrefetcher` stages the pinned-host
  optimizer partition toward the device ahead of the sharded update
  (``zero_optimization.offload_optimizer.pipeline_read``).

Double buffering rides the PR-4 ``GatherWindowCache`` pattern
(:mod:`deepspeed_tpu.runtime.overlap.prefetch`): a ``put`` issues the
device→host copy asynchronously (``copy_to_host_async`` when the payload
is still a jax array) and parks it in a one-slot pending buffer; the NEXT
``put`` (or an explicit :meth:`HostPageTier.sync`) materializes the
previous transfer, by which point the DMA has progressed under compute.
On the CPU simulator every copy is synchronous and the tier degrades to a
plain bounded dict — bit-exactness tests run there.

Fault sites (see :mod:`deepspeed_tpu.runtime.fault.injection`):
``host_alloc`` at buffer admission, ``kv_swap_out`` at D2H issue,
``offload_prefetch`` at the prefetcher's H2D arm.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Hashable, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import logger
from ..fault import injection


class HostPageTier:
    """Bounded host-memory store of canonical-row page payloads.

    Keys are arbitrary hashables (the KV swap manager uses
    ``("kv", uid)`` / ``("prefix", token_path)``); values are float32
    numpy arrays in the ``kv_ship`` canonical row layout.  Capacity is
    enforced in bytes with LRU eviction; a payload larger than the whole
    tier is rejected outright.
    """

    def __init__(self, capacity_bytes: int, name: str = "host_kv"):
        self.capacity_bytes = int(capacity_bytes)
        self.name = name
        self._store: "OrderedDict[Hashable, np.ndarray]" = OrderedDict()
        self._pending: Optional[Tuple[Hashable, Any]] = None
        self.used_bytes = 0
        self.puts = 0
        self.evictions = 0
        self.rejects = 0
        self.swap_out_bytes = 0

    # -- internal -----------------------------------------------------

    def _materialize(self, key: Hashable, payload: Any) -> None:
        """Drain a pending D2H transfer into the store (second buffer
        slot).  ``np.asarray`` blocks until the async copy has landed."""
        rows = np.asarray(payload, dtype=np.float32)
        self._store[key] = rows
        self._store.move_to_end(key)
        self.used_bytes += rows.nbytes
        self.swap_out_bytes += rows.nbytes

    def _evict_until(self, need: int) -> None:
        while self._store and self.capacity_bytes - self.used_bytes < need:
            old_key, old_rows = self._store.popitem(last=False)
            self.used_bytes -= old_rows.nbytes
            self.evictions += 1
            logger.info("host tier %s: evicted %s (%d bytes) for incoming "
                        "spill", self.name, old_key, old_rows.nbytes)

    # -- public -------------------------------------------------------

    @property
    def free_bytes(self) -> int:
        return max(0, self.capacity_bytes - self.used_bytes)

    def put(self, key: Hashable, rows: Any) -> bool:
        """Admit ``rows`` under ``key``.  Returns False when the payload
        cannot fit (too large, or host_alloc fault injected); raises the
        ``kv_swap_out`` site's fault (InjectedSwapFailure / OSError) so
        the caller can take the evict+recompute fallback."""
        try:
            injection.inject("host_alloc")
        except injection.InjectedExhausted:
            self.rejects += 1
            logger.warning("host tier %s: injected host_alloc exhaustion, "
                           "rejecting %s", self.name, key)
            return False
        injection.inject("kv_swap_out")

        nbytes = int(rows.nbytes if hasattr(rows, "nbytes")
                     else np.asarray(rows).nbytes)
        if nbytes > self.capacity_bytes:
            self.rejects += 1
            return False
        # Drain the previous pending transfer first (its DMA has had a full
        # put-interval to progress), then issue this one asynchronously.
        self.sync()
        self.discard(key)
        self._evict_until(nbytes)
        if hasattr(rows, "copy_to_host_async"):
            try:
                rows.copy_to_host_async()
            except Exception:  # CPU backend / already-host arrays
                pass
        self._pending = (key, rows)
        self.puts += 1
        return True

    def sync(self) -> None:
        """Drain the in-flight D2H transfer, if any."""
        if self._pending is not None:
            key, payload = self._pending
            self._pending = None
            self._materialize(key, payload)

    def get(self, key: Hashable) -> Optional[np.ndarray]:
        """Pure lookup (no hit/miss accounting — the caller confirms the
        use, mirroring the prefix cache's note_hit idiom)."""
        self.sync()
        rows = self._store.get(key)
        if rows is not None:
            self._store.move_to_end(key)
        return rows

    def pop(self, key: Hashable) -> Optional[np.ndarray]:
        self.sync()
        rows = self._store.pop(key, None)
        if rows is not None:
            self.used_bytes -= rows.nbytes
        return rows

    def discard(self, key: Hashable) -> None:
        if self._pending is not None and self._pending[0] == key:
            self._pending = None
            return
        rows = self._store.pop(key, None)
        if rows is not None:
            self.used_bytes -= rows.nbytes

    def __contains__(self, key: Hashable) -> bool:
        self.sync()
        return key in self._store

    def __len__(self) -> int:
        self.sync()
        return len(self._store)

    def stats(self) -> Dict[str, int]:
        return {
            "capacity_bytes": self.capacity_bytes,
            "used_bytes": self.used_bytes,
            "entries": len(self._store) + (1 if self._pending else 0),
            "puts": self.puts,
            "evictions": self.evictions,
            "rejects": self.rejects,
            "swap_out_bytes": self.swap_out_bytes,
        }


class HostOffloadPrefetcher:
    """Stages the host-resident optimizer partition toward the device
    ahead of the sharded update (``offload_optimizer.pipeline_read``).

    On TPU the arm is a real async H2D ``jax.device_put`` into device
    memory kind, issued between steps so the transfer hides under the
    forward/backward; on the CPU simulator placement is a no-op and the
    staged tree is the SAME tree (bitwise identity — the offload-vs-
    resident loss equality test runs there).  An injected ``offload``
    fault skips the stage: the update then reads the pinned-host
    partition directly — correct, just unoverlapped.
    """

    def __init__(self) -> None:
        self.arms = 0
        self.failures = 0
        self.bytes_staged = 0
        self._is_tpu = jax.default_backend() == "tpu"

    def arm(self, tree: Any) -> Any:
        """Issue the H2D stage for ``tree``; returns the staged tree (the
        input tree unchanged on CPU or on injected failure)."""
        try:
            injection.inject("offload_prefetch")
        except injection.InjectedOffloadFailure:
            self.failures += 1
            logger.warning("offload prefetch: injected failure, update will "
                           "read the host partition unstaged")
            return tree
        self.arms += 1

        def _nbytes(leaf: Any) -> int:
            return int(getattr(leaf, "nbytes", 0) or 0)

        self.bytes_staged += sum(
            _nbytes(x) for x in jax.tree_util.tree_leaves(tree))
        if not self._is_tpu:
            return tree

        def _stage(leaf: Any) -> Any:
            sharding = getattr(leaf, "sharding", None)
            if sharding is None or getattr(leaf, "ndim", 0) == 0:
                return leaf
            try:
                return jax.device_put(
                    leaf, sharding.with_memory_kind("device"))
            except Exception:
                return leaf

        return jax.tree_util.tree_map(_stage, tree)

    def stats(self) -> Dict[str, int]:
        return {"arms": self.arms, "failures": self.failures,
                "bytes_staged": self.bytes_staged}
