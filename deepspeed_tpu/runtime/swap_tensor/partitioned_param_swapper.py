"""Tensor swap machinery: HBM ↔ host ↔ NVMe.

Reference analogues: ``runtime/swap_tensor/partitioned_param_swapper.py:37``
(AsyncPartitionedParameterSwapper — aio handles, pinned buffers, aligned IO)
and ``partitioned_optimizer_swapper.py:29`` (+ pipelined variant).

TPU version: the device→host leg is ``jax.device_put`` to the host platform
(or ``np.asarray``); the host→disk leg is the native aio engine
(:mod:`deepspeed_tpu.ops.aio`).  Swapping operates on whole pytrees with
per-leaf files under a swap folder, double-buffered via async requests.
"""
from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...ops.aio import AsyncIOHandle, aio_available
from ...utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_folder: str, aio_config=None):
        os.makedirs(swap_folder, exist_ok=True)
        self.swap_folder = swap_folder
        cfg = aio_config
        self.handle = AsyncIOHandle(
            block_size=getattr(cfg, "block_size", 1 << 20),
            queue_depth=getattr(cfg, "queue_depth", 8),
            thread_count=getattr(cfg, "thread_count", 4),
        ) if aio_available() else None
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._pending: List[Any] = []

    def _path(self, name: str) -> str:
        return os.path.join(self.swap_folder, name.replace("/", ".") + ".swp")

    # ---------------------------------------------------------------- #
    def swap_out(self, name: str, tree: Any, blocking: bool = True) -> None:
        """Device pytree → NVMe files. Frees nothing on device by itself —
        the caller drops its references (XLA frees the buffers)."""
        flat, treedef = jax.tree.flatten(tree)
        metas = []
        for i, leaf in enumerate(flat):
            host = np.ascontiguousarray(np.asarray(leaf))
            path = self._path(f"{name}.{i}")
            if self.handle is not None:
                req = self.handle.async_pwrite(host, path)
                self._pending.append(req)
            else:  # pure-python fallback
                host.tofile(path)
            metas.append({"shape": host.shape, "dtype": str(host.dtype),
                          "path": path})
        self._meta[name] = {"treedef": treedef, "leaves": metas}
        if blocking:
            self.synchronize_writes()

    def swap_in(self, name: str, device=None, shardings=None) -> Any:
        """NVMe files → device pytree (with optional target shardings)."""
        meta = self._meta[name]
        leaves = []
        reqs = []
        for lm in meta["leaves"]:
            buf = np.empty(lm["shape"], dtype=np.dtype(lm["dtype"]))
            if self.handle is not None:
                reqs.append((self.handle.async_pread(buf, lm["path"]), buf))
            else:
                buf = np.fromfile(lm["path"], dtype=np.dtype(lm["dtype"])
                                  ).reshape(lm["shape"])
                reqs.append((None, buf))
        for req, buf in reqs:
            if req is not None:
                req.wait()
            leaves.append(buf)
        tree = jax.tree.unflatten(meta["treedef"], leaves)
        if shardings is not None:
            tree = jax.device_put(tree, shardings)
        elif device is not None:
            tree = jax.device_put(tree, device)
        else:
            tree = jax.tree.map(jax.numpy.asarray, tree)
        return tree

    def synchronize_writes(self) -> None:
        for req in self._pending:
            req.wait()
        self._pending.clear()

    def release(self, name: str) -> None:
        meta = self._meta.pop(name, None)
        if meta:
            for lm in meta["leaves"]:
                try:
                    os.remove(lm["path"])
                except OSError:
                    pass

    def cleanup(self) -> None:
        for name in list(self._meta):
            self.release(name)
        shutil.rmtree(self.swap_folder, ignore_errors=True)


# Reference class-name aliases
AsyncPartitionedParameterSwapper = AsyncTensorSwapper
PartitionedOptimizerSwapper = AsyncTensorSwapper
