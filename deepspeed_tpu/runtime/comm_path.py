"""Explicit-communication train path — ZeRO++ (qwZ/qgZ) + sparse gradients.

The engine's fused path lets XLA insert the gradient-mean / reduce-scatter
collectives, which is the right default on TPU.  But three DeepSpeed config
surfaces exist precisely to change the WIRE FORMAT of those collectives, so
when any of them is enabled the loss/grad computation runs under
``shard_map`` over the data axes and the exchanges are written by hand:

  ``zero_quantized_weights`` (qwZ)  — ZeRO-3 bf16 param shards allgather on
      an int8 wire (reference: partition_parameters.py:769 CUDAQuantizer,
      zero/config.py:294).
  ``zero_quantized_gradients`` (qgZ) — gradients exchange as an int4/int8
      reduce-scatter followed by a quantized allgather, with optional LoCo
      error feedback (reference: runtime/comm/coalesced_collectives.py:31
      all_to_all_quant_reduce, :81 LoCo).
  ``sparse_gradients`` — embedding-row gradients exchange as (indices,
      values) pairs instead of the dense [V, D] tensor (reference:
      runtime/sparse_tensor.py:13 + engine.sparse_allreduce_bucket
      engine.py:2636).

Model-parallel composition (reference runs ZeRO++ under Megatron TP,
docs/_tutorials/zeropp.md:13): the step is a PARTIAL-manual ``shard_map`` —
manual over the ZeRO/data axes only (``axis_names=data_axes``), while
tensor/seq/expert stay Auto so the per-shard loss compute remains a global
GSPMD program and XLA keeps inserting the model-parallel collectives exactly
as on the fused path.  Only the pipe axis is rejected (pipeline training has
its own engine and grad exchange).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.quantizer.quantizer import get_quant_fns
from .comm.coalesced_collectives import bucketed_allreduce_coalesced
from .overlap.deferred import DeferredAccumulator
from .sparse_tensor import SparseTensor, sparse_allreduce
from .topology import DATA, DATA_OUTER


def dp_axes_info(topology):
    """Active data-parallel axes + size + the PartitionSpec entry for a
    leading per-rank axis (LoCo error buffers).  Single source of truth for
    engine init and the shard_map specs — they must agree exactly."""
    axes = tuple(a for a in (DATA_OUTER, DATA) if topology.dims.get(a, 1) > 1)
    n = 1
    for a in axes:
        n *= topology.dims[a]
    entry = axes if len(axes) > 1 else (axes[0] if axes else None)
    return axes, n, entry


# --------------------------------------------------------------------- #
# Wire primitives (must run inside shard_map with ``axes`` bound)
# --------------------------------------------------------------------- #
def loco_partition_size(numel: int, n: int, group_size: int = 256) -> int:
    """Length of one rank's reduced partition (stage-2 LoCo buffer size)."""
    pad = (-numel) % (n * group_size)
    return (numel + pad) // n


def quantized_allreduce(grad: jnp.ndarray, axes, bits: int = 8,
                        group_size: int = 256,
                        error: Optional[jnp.ndarray] = None,
                        server_error: Optional[jnp.ndarray] = None,
                        fused: bool = True
                        ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray],
                                   Optional[jnp.ndarray]]:
    """Mean-allreduce with a fully quantized wire (qgZ analogue).

    Stage 1: each rank quantizes its local contribution and all-to-alls it;
    stage 2: the reduced partition is re-quantized and allgathered.  With
    LoCo, BOTH hops carry error feedback (reference coalesced_collectives
    loco variant): ``error`` holds the stage-1 residual of my local
    contribution, ``server_error`` the stage-2 residual of my reduced
    partition.

    ``fused=True`` (default) runs both hops through the EQuARX-style fused
    kernels (``comm/fused_wire.py``): one Pallas scale+quantize+pack pass
    produces each collective's operand directly and a fused
    unpack+dequant+mean consumes the stage-1 exchange — bit-identical
    values under jit, no full-precision intermediates between quantize and
    exchange.  ``fused=False`` keeps the legacy jnp-composed wire (the
    parity baseline).
    """
    n = jax.lax.psum(1, axes)
    if n <= 1:
        return grad, error, server_error
    if fused:
        from .comm.fused_wire import fused_quantized_allreduce

        return fused_quantized_allreduce(grad, axes, bits=bits,
                                         group_size=group_size, error=error,
                                         server_error=server_error)
    quant, dequant = get_quant_fns(bits)
    flat = grad.reshape(-1).astype(jnp.float32)
    if error is not None:
        flat = flat + error.reshape(-1)
    size = flat.shape[0]
    pad = (-size) % (n * group_size)
    if pad:
        flat = jnp.pad(flat, (0, pad))

    # stage 1: quantize local contributions, exchange, reduce my partition
    q, s = quant(flat, group_size)                 # wire: int(size) + f32 scales
    new_error = None
    if error is not None:
        sent = dequant(q, s, shape=flat.shape)     # what actually hit the wire
        new_error = (flat - sent)[:size].reshape(grad.shape)
    per = flat.shape[0] // n
    groups_per = q.shape[0] // n
    q_x = jax.lax.all_to_all(q.reshape(n, groups_per, -1), axes,
                             split_axis=0, concat_axis=0, tiled=True)
    s_x = jax.lax.all_to_all(s.reshape(n, groups_per, 1), axes,
                             split_axis=0, concat_axis=0, tiled=True)
    contribs = dequant(q_x.reshape(n * groups_per, -1),
                       s_x.reshape(n * groups_per, 1)).reshape(n, per)
    mine = jnp.mean(contribs, axis=0)              # my reduced partition

    # stage 2: quantized allgather of the reduced partitions
    new_server_error = None
    if server_error is not None:
        mine = mine + server_error.reshape(-1)
    q2, s2 = quant(mine, group_size)
    if server_error is not None:
        sent2 = dequant(q2, s2, shape=mine.shape)
        new_server_error = (mine - sent2).reshape(server_error.shape)
    q2_all = jax.lax.all_gather(q2, axes, axis=0, tiled=False)   # [n, g, w]
    s2_all = jax.lax.all_gather(s2, axes, axis=0, tiled=False)
    full = dequant(q2_all.reshape(-1, q2.shape[1]),
                   s2_all.reshape(-1, 1)).reshape(-1)[:size]
    return (full.reshape(grad.shape).astype(grad.dtype), new_error,
            new_server_error)


def quantized_all_gather_shard(shard: jnp.ndarray, axes, dim: int,
                               bits: int = 8, group_size: int = 256,
                               out_dtype=jnp.bfloat16,
                               fused: bool = True) -> jnp.ndarray:
    """qwZ: reconstruct a full parameter from its ZeRO-3 shard over an int8
    wire.  ``dim`` is the sharded dimension; shards must be equal-size.
    ``fused`` as in :func:`quantized_allreduce`."""
    n = jax.lax.psum(1, axes)
    if n <= 1:
        return shard.astype(out_dtype)
    if fused:
        from .comm.fused_wire import fused_quantized_all_gather

        vals = fused_quantized_all_gather(
            shard, axes, bits=bits, group_size=group_size,
            out_dtype=out_dtype)
        pieces = vals.reshape((n,) + shard.shape)
        return jnp.concatenate([pieces[i] for i in range(n)], axis=dim)
    quant, dequant = get_quant_fns(bits)
    flat = shard.reshape(-1)
    q, s = quant(flat, group_size)
    q_all = jax.lax.all_gather(q, axes, axis=0, tiled=False)     # [n, g, w]
    s_all = jax.lax.all_gather(s, axes, axis=0, tiled=False)
    vals = dequant(q_all.reshape(-1, q.shape[1]), s_all.reshape(-1, 1),
                   dtype=out_dtype).reshape(n, -1)[:, :flat.shape[0]]
    pieces = vals.reshape((n,) + shard.shape)
    return jnp.concatenate([pieces[i] for i in range(n)], axis=dim)


def sparse_embedding_allreduce(grad: jnp.ndarray, token_ids: jnp.ndarray,
                               axes) -> jnp.ndarray:
    """Mean-allreduce an embedding-row gradient as (indices, values) pairs.

    Exact only when the grad's nonzero rows are the batch's tokens — true
    for a pure input embedding, FALSE for tied embeddings (the lm-head
    matmul makes the grad dense over the whole vocab); the step builder
    refuses the sparse wire for tied-embedding models.  Wire volume:
    T·(D+1) vs V·D dense."""
    max_nnz = min(int(token_ids.size), grad.shape[0])
    sp = SparseTensor.from_dense(grad, max_nnz)
    return sparse_allreduce(sp, axes)


# --------------------------------------------------------------------- #
# Engine step builder
# --------------------------------------------------------------------- #
def _sharded_dim(spec, zero_axes) -> Optional[int]:
    """Which dim of a param spec carries the ZeRO axes (None = replicated)."""
    if spec is None:
        return None
    zset = set(zero_axes)
    for d, entry in enumerate(spec):
        entries = entry if isinstance(entry, (tuple, list)) else (entry,)
        if any(a in zset for a in entries if a is not None):
            return d
    return None


class _WireContext:
    """Shared machinery for the explicit-comm step builders (fused
    train_batch and imperative backward()/step()): config parsing, mesh
    gating, the qwZ param gather, and the per-leaf gradient wire."""

    def __init__(self, engine):
        cfg = engine.config
        self.engine = engine
        self.topo = topo = engine.topology
        zc = cfg.zero_config
        self.qwz = bool(zc.zero_quantized_weights)
        self.qgz = bool(zc.zero_quantized_gradients)
        self.loco = bool(getattr(zc, "zeropp_loco", False))
        self.sparse = bool(getattr(cfg, "sparse_gradients_enabled", False))
        self.grad_bits = 4   # qgZ wire (reference quant_reduce.cu uses int4)
        if self.sparse and bool(getattr(getattr(engine.module, "config", None),
                                        "tie_embeddings", False)):
            from ..utils.logging import logger

            logger.warning(
                "sparse_gradients disabled: tied embeddings make the "
                "embedding grad dense over the vocab (lm-head rows), "
                "so a token-indexed sparse exchange would drop mass")
            self.sparse = False

        if topo.dims.get("pipe", 1) > 1:
            raise ValueError(
                "explicit-comm path (zero_quantized_*/sparse_gradients) does "
                "not compose with pipeline parallelism — the pipeline engine "
                "owns its own gradient exchange; use the fused path with "
                "pipe>1")
        self.data_axes, self.n_dp, self.dp_axes_entry = dp_axes_info(topo)
        self.manual = set(self.data_axes)
        self.gas = engine.gradient_accumulation_steps()

        # comm/compute overlap (runtime/overlap/): bucketed plain-psum
        # exchange + one-iteration-deferred micro reduction.  Settings come
        # from the manager so an auto-mode re-tune changes the next build.
        mgr = getattr(engine, "overlap", None)
        self.overlap_mgr = mgr
        overlap_on = bool(mgr is not None and mgr.enabled)
        self.bucket_bytes = int(mgr.bucket_bytes) if overlap_on else 0
        self.overlap_deferred = overlap_on and bool(mgr.deferred)

        # collective algorithm/wire (runtime/comm/hierarchical.py): the
        # manager resolves {flat, 2hop} from the topology slice model +
        # rooflines (or the config forces it); quantized wire bits for the
        # PLAIN-grad leaves come from overlap.wire_bits / the auto
        # selector — config qgZ keeps its own per-leaf wire below.
        from .comm.hierarchical import hop_axes

        self.group_size = 256
        if overlap_on:
            mgr.resolve_comm(engine)
        self.wire_bits = int(mgr.comm_wire_bits) if overlap_on else 0
        self.intra_axes, self.inter_axes = hop_axes(topo, self.data_axes)
        algo = mgr.comm_algo if (overlap_on and mgr.comm_algo) else "flat"
        self.algo_2hop = bool(algo == "2hop" and self.intra_axes
                              and self.inter_axes)
        #: fused-gemm epilogue schedule for the plain-grad exchange (the
        #: leaf seam's degenerate edge; TP/ZeRO-3 call sites that own the
        #: producing matmul use comm/fused_gemm.py wrappers directly)
        self.algo_fused_gemm = bool(algo == "fused_gemm")

        self.params_t = engine.state.params
        self.stage3 = engine.zero_stage >= 3
        param_specs = engine.plan.param_specs(self.params_t)
        zero_axes = engine.plan.zero_axes
        self._check_stage3_axes(zero_axes)
        self.zero_axes = zero_axes
        self.shard_dims = jax.tree.map(
            lambda s: _sharded_dim(s, zero_axes), param_specs,
            is_leaf=lambda x: isinstance(x, P))
        self.param_in = jax.tree.map(self.restrict_spec, param_specs,
                                     is_leaf=lambda x: isinstance(x, P)) \
            if self.stage3 else P()
        self.err_spec = P(self.dp_axes_entry) if self.loco else None

    # ------------------------------------------------------------------ #
    def restrict_spec(self, spec):
        """Keep only manual (data) axes of a spec.  Partial-manual shard_map
        in/out specs may only name manual axes; the model-parallel sharding
        (tensor/seq/expert entries) rides in on each array's own
        NamedSharding and stays under GSPMD inside the body."""
        if spec is None:
            return P()
        out = []
        for entry in spec:
            entries = entry if isinstance(entry, (tuple, list)) else (entry,)
            kept = tuple(a for a in entries if a in self.manual)
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        return P(*out)

    def batch_spec_fn(self, batch_dim):
        def batch_spec(x):
            spec = [None] * x.ndim
            if self.data_axes:
                spec[batch_dim] = self.dp_axes_entry
            return P(*spec)

        return batch_spec

    def shard_mapped(self, body, in_specs, out_specs):
        """Partial-manual shard_map over the data axes (plain GSPMD body
        when dp=1: axis_names={} would mean ALL axes manual — wrong for a
        pure model-parallel mesh)."""
        if not self.data_axes:
            return body
        from .topology import compat_shard_map

        return compat_shard_map(body, mesh=self.topo.mesh,
                                in_specs=tuple(in_specs),
                                out_specs=out_specs,
                                manual_axes=self.manual)

    def gather_full(self, params_local):
        """Local shards → full compute-dtype params (qwZ wire if enabled)."""
        engine = self.engine

        def leaf(x, d):
            if d is None:
                return x.astype(engine.compute_dtype)
            xb = x.astype(engine.compute_dtype)
            if self.qwz:
                return quantized_all_gather_shard(
                    xb, self.zero_axes, d, bits=8,
                    out_dtype=engine.compute_dtype)
            return jax.lax.all_gather(xb, self.zero_axes, axis=d, tiled=True)

        return jax.tree.map(leaf, params_local, self.shard_dims)

    def exchange_grads(self, grads, batch, comm_error):
        """Per-leaf wire selection: sparse rows for embeddings, quantized
        allreduce for the rest (or plain psum-mean when qgZ is off).

        LoCo error leaves carry a leading per-device axis of size 1 inside
        shard_map (stored sharded over the data axes outside)."""
        data_axes, loco = self.data_axes, self.loco
        ids = None
        if self.sparse and isinstance(batch, dict):
            ids = batch.get("input_ids")
        n = jax.lax.psum(1, data_axes) if data_axes else 1

        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        err_flat = treedef.flatten_up_to(comm_error) if loco else \
            [None] * len(flat)
        outs, errs = [None] * len(flat), []
        plain = []   # indices riding the plain-psum wire (bucketable)
        for idx, ((path, g), e) in enumerate(zip(flat, err_flat)):
            is_embed = any("embed" in str(getattr(k, "key", "")).lower()
                           for k in path)
            if self.sparse and is_embed and ids is not None and g.ndim == 2 \
                    and data_axes:
                outs[idx] = sparse_embedding_allreduce(g, ids, data_axes)
                errs.append(e)
            elif self.qgz and data_axes:
                if self.algo_2hop:
                    from .comm.hierarchical import two_hop_allreduce

                    out, new_w, new_s = two_hop_allreduce(
                        g, self.intra_axes, self.inter_axes,
                        wire_bits=self.grad_bits,
                        group_size=self.group_size,
                        error=e["worker"][0] if loco else None,
                        server_error=e["server"][0] if loco else None)
                else:
                    out, new_w, new_s = quantized_allreduce(
                        g, data_axes, bits=self.grad_bits,
                        error=e["worker"][0] if loco else None,
                        server_error=e["server"][0] if loco else None)
                outs[idx] = out
                errs.append({"worker": new_w[None], "server": new_s[None]}
                            if loco else e)
            elif data_axes:
                plain.append(idx)
                errs.append(e)
            else:
                outs[idx] = g
                errs.append(e)
        if plain:
            leaves = [flat[i][1] for i in plain]
            for i, v in zip(plain, self._plain_psum_mean(leaves, n)):
                outs[i] = v
        new_error = treedef.unflatten(errs) if loco else None
        return treedef.unflatten(outs), new_error

    def _plain_psum_mean(self, leaves, n):
        """Mean-allreduce the plain-wire leaves with the selected
        algorithm/wire — one exchange per size bucket when
        ``overlap.bucket_bytes`` is set.  With algo=flat and wire_bits=0
        this is the classic bucketed psum (bit-identical to per-leaf);
        2-hop and quantized wires route through
        ``comm/hierarchical.exchange_leaves`` (the seam the comm_sweep
        bench measures)."""
        if self.algo_2hop or self.algo_fused_gemm or self.wire_bits:
            from .comm.hierarchical import exchange_leaves

            algo = "2hop" if self.algo_2hop else \
                ("fused_gemm" if self.algo_fused_gemm else "flat")
            exchanged, stats = exchange_leaves(
                leaves, self.data_axes, self.intra_axes, self.inter_axes,
                algo, self.wire_bits,
                group_size=self.group_size,
                bucket_bytes=self.bucket_bytes, n=n)
            if self.overlap_mgr is not None and self.bucket_bytes > 0:
                self.overlap_mgr.note_bucket_plan(stats)
            return exchanged
        if self.bucket_bytes > 0:
            exchanged, stats = bucketed_allreduce_coalesced(
                leaves, self.data_axes, self.bucket_bytes, n=n)
            if self.overlap_mgr is not None:   # trace-time, host side
                self.overlap_mgr.note_bucket_plan(stats)
            return exchanged
        return [jax.lax.psum(g, self.data_axes) / n for g in leaves]

    def local_loss_and_grads(self, params_full, batch, rng, scaler_state):
        """LOCAL full-shape grads (no cross-device reduction over the manual
        data axes; Auto-axis reductions — tensor partials, seq shards — are
        inserted by XLA inside the body).

        Differentiates w.r.t. the GATHERED params — autodiff must not flow
        through the quantize→round→dequantize wire (round has zero
        gradient), and full-shape grads are what the exchange and the
        (logically full, sharded-layout) optimizer update both expect.
        """
        engine = self.engine

        def scaled_loss(p):
            out = engine.loss_fn(p, batch, rng)
            loss = out[0] if isinstance(out, tuple) else out
            return engine.loss_scaler.scale_loss(
                loss.astype(jnp.float32), scaler_state), loss

        grads, loss = jax.grad(scaled_loss, has_aux=True)(params_full)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        return loss, grads

    def _check_stage3_axes(self, zero_axes):
        # ZeRO-3 shards params over the full DP×SP group (data, expert, seq);
        # the explicit gather wire runs over MANUAL axes, but seq/expert must
        # stay Auto so the loss compute remains a global GSPMD program
        # (attention needs the full sequence; MoE routing the expert axis).
        # An all_gather over an Auto axis is ill-formed — so stage 3 quantized
        # wires require the ZeRO group to be pure data axes.
        if self.stage3 and not set(zero_axes) <= self.manual:
            raise ValueError(
                f"explicit-comm at ZeRO stage 3 requires params sharded over "
                f"data axes only, got zero_axes={zero_axes} (mesh has "
                f"seq/expert > 1); use stage<=2 wires or the fused path on "
                f"this mesh")

    def guard_loco_errors(self, new_error, old_error, grads):
        """A skipped (overflow) step must not commit inf/nan residuals —
        they would poison every subsequent corrected gradient."""
        engine = self.engine
        overflow = engine.loss_scaler.check_overflow(grads) \
            if engine.loss_scaler.dynamic else jnp.zeros((), bool)
        return jax.tree.map(
            lambda new, old: jnp.where(overflow, old, new),
            new_error, old_error)


def _wire_ctx(engine) -> _WireContext:
    """One _WireContext per engine, shared by the three step builders (the
    parsing/spec trees are identical and the tied-embeddings warning should
    fire once)."""
    ctx = getattr(engine, "_wire_ctx_cache", None)
    if ctx is None or ctx.engine is not engine:
        ctx = _WireContext(engine)
        engine._wire_ctx_cache = ctx
    return ctx


def build_explicit_comm_step(engine, _force_eager_micro: bool = False):
    """Build the shard_map'd train-batch step for the explicit-comm config
    surface.  Mirrors engine._build_train_batch_fn's semantics (micro-step
    scan, loss scaling, clipping, overflow skip) with hand-written wires.

    With overlap's deferred reduction on (plain wire, gas > 1), each
    micro-batch's psum is double-buffered in the scan carry so collective
    *i* overlaps compute *i+1* (``overlap/deferred.py``); quantized/LoCo/
    sparse wires keep the single boundary exchange — a per-micro quantized
    exchange would change the wire numerics, not just the schedule.
    ``_force_eager_micro`` is the test seam proving deferred and eager
    *issuance* of the same per-micro schedule produce bit-identical
    gradients.  Note the schedule itself differs from overlap-off: off
    exchanges once at the boundary (``psum(Σ g_i)/n``), deferred exchanges
    per micro-batch (``Σ psum(g_i)/n``) — the same mean with a different
    fp summation order, so toggling ``deferred_grad_reduce`` on the
    explicit wire is reproducible-schedule-for-schedule, not bitwise
    against the boundary schedule.  (The FUSED path's overlap toggle is
    bitwise end-to-end: only the sharding constraint moves.)
    """
    ctx = _wire_ctx(engine)
    gas, data_axes, loco = ctx.gas, ctx.data_axes, ctx.loco
    params_t = ctx.params_t
    # deferred per-micro reduction: only the plain mean-psum wire is linear
    # and stateless enough to fire per micro-batch without changing values
    micro_wire = bool((ctx.overlap_deferred or _force_eager_micro)
                      and gas > 1 and data_axes
                      and not (ctx.qgz or ctx.loco or ctx.sparse)
                      # an auto-selected quantized plain wire exchanges
                      # once at the boundary too: a per-micro quantize
                      # would change the wire numerics, not the schedule
                      and ctx.wire_bits == 0)
    engine._deferred_active = bool(micro_wire and not _force_eager_micro)
    if ctx.overlap_deferred and gas > 1 and not micro_wire:
        from ..utils.logging import logger

        logger.info("overlap.deferred_grad_reduce: quantized/LoCo/sparse "
                    "wires exchange once at the boundary — per-micro "
                    "deferral skipped (schedule-only deferral would change "
                    "those wires' numerics)")

    def local_step(params_local, batch, rng, scaler_state, comm_error):
        params_full = ctx.gather_full(jax.lax.stop_gradient(params_local))
        exchanged = False
        if gas == 1:
            loss, grads = ctx.local_loss_and_grads(params_full, batch, rng,
                                                   scaler_state)
            mean_loss = loss
        else:
            zeros = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                                 params_t)
            if micro_wire:
                n = jax.lax.psum(1, data_axes)

                def exchange(tree):
                    leaves, tdef = jax.tree_util.tree_flatten(tree)
                    return tdef.unflatten(ctx._plain_psum_mean(leaves, n))

                reducer = DeferredAccumulator(exchange, zeros)

                if _force_eager_micro:
                    def micro(carry, mb):
                        acc, r = carry
                        r, r2 = jax.random.split(r)
                        loss, g = ctx.local_loss_and_grads(
                            params_full, mb, r2, scaler_state)
                        acc = jax.tree.map(jnp.add, acc, exchange(g))
                        return (acc, r), loss

                    (grads, _), losses = jax.lax.scan(
                        micro, (zeros, rng), batch)
                else:
                    def micro(carry, mb):
                        acc, pending, r = carry
                        r, r2 = jax.random.split(r)
                        loss, g = ctx.local_loss_and_grads(
                            params_full, mb, r2, scaler_state)
                        acc, pending = reducer.step((acc, pending), g)
                        return (acc, pending, r), loss

                    (acc, pending, _), losses = jax.lax.scan(
                        micro, (zeros, zeros, rng), batch)
                    grads = reducer.flush((acc, pending))
                exchanged = True
            else:
                def micro(carry, mb):
                    acc, r = carry
                    r, r2 = jax.random.split(r)
                    loss, g = ctx.local_loss_and_grads(params_full, mb, r2,
                                                       scaler_state)
                    return (jax.tree.map(jnp.add, acc, g), r), loss

                (grads, _), losses = jax.lax.scan(micro, (zeros, rng), batch)
            grads = jax.tree.map(lambda g: g / gas, grads)
            mean_loss = losses.mean()

        # Unscale BEFORE the wire: LoCo residuals must live in true gradient
        # units, or a dynamic-loss-scale change would make the carried error
        # wrong by the scale ratio.  (With the per-micro wire the exchange
        # already ran on scaled grads — psum is linear, so unscaling after
        # is the same mean in true units.)
        grads = engine.loss_scaler.unscale_grads(grads, scaler_state)
        if exchanged:
            new_error = comm_error
        else:
            flat_batch = batch if gas == 1 else \
                jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), batch)
            grads, new_error = ctx.exchange_grads(grads, flat_batch,
                                                  comm_error)
        mean_loss = jax.lax.pmean(mean_loss, data_axes) if data_axes else mean_loss
        return mean_loss, grads, new_error

    batch_spec = ctx.batch_spec_fn(batch_dim=0 if gas == 1 else 1)

    def step_fn(state, batch):
        rng, sub = jax.random.split(state.rng)
        args = [state.params, batch, sub, state.scaler]
        in_specs = [ctx.param_in, jax.tree.map(batch_spec, batch), P(), P()]
        out_specs = (P(), P(), ctx.err_spec) if loco else (P(), P())

        if loco:
            body = local_step
            args.append(state.comm_error)
            in_specs.append(ctx.err_spec)
        else:
            def body(p, b, r, sc):
                loss, grads, _ = local_step(p, b, r, sc, None)
                return loss, grads

        res = ctx.shard_mapped(body, in_specs, out_specs)(*args)
        loss, grads = res[0], res[1]
        new_error = res[2] if loco else None
        grads = engine._constrain_grads(grads)
        new_state = engine._apply_update(state, grads, unscale=False)
        if loco:
            new_error = ctx.guard_loco_errors(new_error, state.comm_error,
                                              grads)
        new_state = new_state.replace(micro_step=state.micro_step + gas,
                                      rng=rng, comm_error=new_error)
        return new_state, loss

    return jax.jit(step_fn, donate_argnums=(0,))


# --------------------------------------------------------------------- #
# Imperative path (backward()/step() wire parity — reference
# engine.py:2048-2085 allreduce_gradients at the accumulation boundary)
# --------------------------------------------------------------------- #
def make_explicit_grad_acc(engine):
    """Per-rank gradient accumulator for the imperative explicit-comm path.

    backward() accumulates LOCAL (per data-shard) grads; the wire exchange
    happens once at the step() boundary — matching the reference, which
    accumulates locally and allreduces in allreduce_gradients().  A
    per-rank-different value can't live outside the manual region as a
    replicated array, so leaves carry a leading [n_dp] axis sharded over
    the data axes (each device holds its own [1, ...] slice)."""
    from jax.sharding import NamedSharding

    _, n_dp, dp_entry = dp_axes_info(engine.topology)
    params = engine.state.params

    def mk(x):
        return jnp.zeros((max(n_dp, 1),) + x.shape, jnp.float32)

    sharding = NamedSharding(engine.topology.mesh, P(dp_entry))
    return jax.jit(lambda p: jax.tree.map(mk, p),
                   out_shardings=sharding)(params)


def build_explicit_micro_fn(engine, pregathered: bool = False):
    """backward() under explicit comm: accumulate SCALED local grads into
    the per-rank accumulator; no cross-data-axis communication here (the
    qwZ param gather still runs — stage 3 needs full params to compute).

    ``pregathered=True`` builds the weight-prefetch variant: the micro fn
    takes the already-gathered full params as a third argument (produced
    once per accumulation window by :func:`build_param_gather_fn` and
    cached by the engine's :class:`~.overlap.prefetch.GatherWindowCache`),
    so the per-micro-step program carries **no** param all-gather.
    """
    ctx = _wire_ctx(engine)
    acc_spec = P(ctx.dp_axes_entry)

    def grads_body(params_full, acc, batch, rng, scaler_state):
        loss, grads = ctx.local_loss_and_grads(params_full, batch, rng,
                                               scaler_state)
        new_acc = jax.tree.map(lambda a, g: a + g[None].astype(a.dtype),
                               acc, grads)
        if ctx.data_axes:
            loss = jax.lax.pmean(loss, ctx.data_axes)
        return loss, new_acc

    def body(params_local, acc, batch, rng, scaler_state):
        params_full = ctx.gather_full(jax.lax.stop_gradient(params_local))
        return grads_body(params_full, acc, batch, rng, scaler_state)

    batch_spec = ctx.batch_spec_fn(batch_dim=0)

    if pregathered:
        def micro_fn(state, batch, params_full):
            rng, sub = jax.random.split(state.rng)
            fn = ctx.shard_mapped(
                grads_body,
                in_specs=[P(), acc_spec,
                          jax.tree.map(batch_spec, batch), P(), P()],
                out_specs=(P(), acc_spec))
            loss, new_acc = fn(params_full, state.grad_acc, batch, sub,
                               state.scaler)
            return state.replace(grad_acc=new_acc,
                                 micro_step=state.micro_step + 1,
                                 rng=rng), loss

        # params_full is deliberately NOT donated — the window cache
        # reuses it across every micro-step until the optimizer step
        return jax.jit(micro_fn, donate_argnums=(0,))

    def micro_fn(state, batch):
        rng, sub = jax.random.split(state.rng)
        fn = ctx.shard_mapped(
            body,
            in_specs=[ctx.param_in, acc_spec,
                      jax.tree.map(batch_spec, batch), P(), P()],
            out_specs=(P(), acc_spec))
        loss, new_acc = fn(state.params, state.grad_acc, batch, sub,
                           state.scaler)
        return state.replace(grad_acc=new_acc,
                             micro_step=state.micro_step + 1, rng=rng), loss

    return jax.jit(micro_fn, donate_argnums=(0,))


def build_param_gather_fn(engine):
    """One jitted qwZ/plain gather of the full compute-dtype params — the
    weight-prefetch cache's miss path.  Run once per accumulation window
    (params only change at the optimizer step) and fed to the
    ``pregathered`` micro fn, this removes (gas - 1) of every window's
    param all-gathers on the imperative explicit path."""
    ctx = _wire_ctx(engine)

    def body(params_local):
        return ctx.gather_full(jax.lax.stop_gradient(params_local))

    fn = ctx.shard_mapped(body, in_specs=[ctx.param_in], out_specs=P())
    return jax.jit(fn)


def build_explicit_step_fn(engine):
    """step() under explicit comm: unscale + mean the accumulated local
    grads, run the quantized wire exchange once, then the optimizer update.

    The sparse embedding wire is a train_batch()-only optimization — it
    needs the batch's token ids, which the boundary no longer has; under
    the imperative API embedding grads ride the dense (quantized) wire."""
    ctx = _wire_ctx(engine)
    gas, loco = ctx.gas, ctx.loco
    acc_spec = P(ctx.dp_axes_entry)

    def body(acc, scaler_state, comm_error):
        grads = jax.tree.map(lambda a: a[0], acc)
        grads = engine.loss_scaler.unscale_grads(grads, scaler_state)
        grads = jax.tree.map(lambda g: g / gas, grads)
        grads, new_error = ctx.exchange_grads(grads, None, comm_error)
        if loco:
            return grads, new_error
        return grads

    def step_fn(state):
        args = [state.grad_acc, state.scaler]
        in_specs = [acc_spec, P()]
        out_specs = (P(), ctx.err_spec) if loco else P()
        if loco:
            args.append(state.comm_error)
            in_specs.append(ctx.err_spec)
        else:
            def no_err_body(acc, sc):
                return body(acc, sc, None)
        res = ctx.shard_mapped(body if loco else no_err_body,
                               in_specs, out_specs)(*args)
        grads = res[0] if loco else res
        new_error = res[1] if loco else None
        grads = engine._constrain_grads(grads)
        new_state = engine._apply_update(state, grads, unscale=False)
        if loco:
            new_error = ctx.guard_loco_errors(new_error, state.comm_error,
                                              grads)
            new_state = new_state.replace(comm_error=new_error)
        zeros = jax.tree.map(jnp.zeros_like, state.grad_acc)
        return new_state.replace(grad_acc=zeros)

    return jax.jit(step_fn, donate_argnums=(0,))
