"""Activation checkpointing (reference: runtime/activation_checkpointing/
checkpointing.py:124,377,488,704,948,1029).

On TPU every reference feature maps onto a ``jax.checkpoint`` policy:

  ====================================  =======================================
  reference knob                        TPU mechanism
  ====================================  =======================================
  ``checkpoint()`` (reentrant)          ``jax.checkpoint`` (remat) of the layer
  ``non_reentrant_checkpoint``          same — JAX remat is always functional
  ``partition_activations``             save residuals sharded over TP/SP axes
                                        (``checkpoint_policies`` + sharding
                                        constraints on saved values)
  ``cpu_checkpointing``                 ``offload_checkpoint_policy`` — saved
                                        residuals live in host memory
  ``contiguous_memory_optimization``    XLA's allocator already packs remat
                                        buffers; accepted as a no-op knob
  ``CudaRNGStatesTracker``              functional PRNG keys — dropout keys are
                                        split per call, replayed exactly under
                                        remat (no tracker needed)
  ====================================  =======================================
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from ...utils.logging import logger

_CONFIG = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize": False,
    "profile": False,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference: checkpointing.py:1029 — set module-level policy flags."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _CONFIG["partition_activations"] = ac.partition_activations
            _CONFIG["contiguous_memory_optimization"] = ac.contiguous_memory_optimization
            _CONFIG["cpu_checkpointing"] = ac.cpu_checkpointing
            _CONFIG["number_checkpoints"] = ac.number_checkpoints
            _CONFIG["synchronize"] = ac.synchronize_checkpoint_boundary
            _CONFIG["profile"] = ac.profile
    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize), ("profile", profile)]:
        if val is not None:
            _CONFIG[key] = val


def is_configured() -> bool:
    return True


#: ``jax.ad_checkpoint.checkpoint_name`` tags the models place on their
#: per-layer residual streams — the values the save/offload policies below
#: select by name (models/transformer.py layer()).
RESIDUAL_NAMES = ("attn_residual", "mlp_residual")


def active() -> bool:
    """True when the DS config asked for a policy beyond plain recompute —
    the signal for model code to route its remat policy through
    :func:`get_policy` instead of its own ``cfg.remat_policy``."""
    return bool(_CONFIG["partition_activations"] or
                _CONFIG["cpu_checkpointing"])


def get_policy(policy_name: Optional[str] = None):
    """Map config → jax.checkpoint policy.

    - ``cpu_checkpointing`` → offload the named residuals to pinned host
      memory during the forward, fetch them back for the backward
      (reference :948's checkpoint-in-cpu, as an XLA memory-space move
      instead of an explicit D2H copy).
    - ``partition_activations`` → SAVE the named residuals instead of
      recomputing; the model constrains them sharded over the mesh's
      data/seq axes, so each device holds only its shard (the reference's
      TP-partitioned saved activations, expressed as sharding).
    - otherwise full recompute (``nothing_saveable``).
    """
    policies = jax.checkpoint_policies
    if policy_name:
        return getattr(policies, policy_name)
    if _CONFIG["cpu_checkpointing"]:
        try:
            return policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=list(RESIDUAL_NAMES),
                offload_src="device", offload_dst="pinned_host")
        except Exception:  # older jax
            logger.warning("offload remat policy unavailable; saving on device")
            return policies.save_only_these_names(*RESIDUAL_NAMES)
    if _CONFIG["partition_activations"]:
        return policies.save_only_these_names(*RESIDUAL_NAMES)
    return policies.nothing_saveable


def checkpoint(function: Callable, *args, policy=None, prevent_cse: bool = True):
    """Reference: checkpointing.py:948 — remat ``function`` over ``args``.

    Returns the function outputs; gradients recompute the forward.
    """
    wrapped = jax.checkpoint(function, policy=policy or get_policy(),
                             prevent_cse=prevent_cse)
    return wrapped(*args)


def checkpoint_wrapper(function: Callable, policy=None) -> Callable:
    """Decorator form used by model code (per-layer remat)."""
    return jax.checkpoint(function, policy=policy or get_policy())


def partition_activations_enabled() -> bool:
    return bool(_CONFIG["partition_activations"])


class CheckpointFunction:
    """API-parity shim for the reference autograd.Function (:488)."""

    @staticmethod
    def apply(run_function, *args):
        return checkpoint(run_function, *args)


def model_parallel_cuda_manual_seed(seed: int):
    """Reference RNG tracker entry point (:124). Functional JAX PRNG needs no
    global tracker; provided for API compatibility."""
    return jax.random.PRNGKey(seed)


def reset():
    for k, v in [("partition_activations", False),
                 ("contiguous_memory_optimization", False),
                 ("cpu_checkpointing", False), ("number_checkpoints", None),
                 ("synchronize", False), ("profile", False)]:
        _CONFIG[k] = v
